"""Trainer/DeviceWorker family over the PS (reference trainer.h:101,
device_worker.h Hogwild/DownpourWorker) + AOT engine cache in the
predictor (serialized-TRT-engine analog)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import PSClient, PSServer
from paddle_tpu.distributed.ps.trainer import (DownpourTrainer,
                                               HogwildTrainer,
                                               TrainerDesc)


@pytest.fixture()
def ps():
    s = PSServer()
    c = PSClient([s.endpoint])
    yield c
    c.close()
    s.stop()


def test_hogwild_trainer_runs_all_batches():
    counts = []
    import threading

    lock = threading.Lock()

    def train_fn(batch, wid):
        with lock:
            counts.append((wid, batch))

    desc = TrainerDesc(thread_num=3)
    HogwildTrainer(desc).run(range(12), train_fn).finalize()
    assert len(counts) == 12
    assert {w for w, _ in counts} == {0, 1, 2}


def test_hogwild_trainer_propagates_worker_error():
    def train_fn(batch, wid):
        if batch == 3:
            raise ValueError("bad batch")

    desc = TrainerDesc(thread_num=2)
    with pytest.raises(RuntimeError, match="worker .* failed"):
        HogwildTrainer(desc).run(range(6), train_fn).finalize()


def test_downpour_trainer_ctr_style(ps):
    """Multi-threaded async sparse training converges: each worker
    pulls rows, computes a grad, pushes async."""
    ps.create_sparse_table("ctr", emb_dim=4, initializer="zeros")
    desc = TrainerDesc(thread_num=2, async_push=True, lr=1.0)
    trainer = DownpourTrainer(desc, ps)
    rng = np.random.RandomState(0)
    batches = [rng.randint(0, 50, (8,)).astype(np.int64)
               for _ in range(10)]

    def train_fn(ids, wid):
        rows = trainer.pull_sparse("ctr", ids)
        grad = np.ones_like(rows)  # push toward -1 per touch
        trainer.push_sparse("ctr", ids, grad)

    trainer.run(batches, train_fn).finalize()
    touched = np.unique(np.concatenate(batches))
    rows = ps.pull_sparse("ctr", touched)
    assert (rows < 0).all()  # every touched row moved negative
    assert ps.sparse_size("ctr") == len(touched)


def test_predictor_aot_engine_cache(tmp_path):
    """Config.set_optim_cache_dir: first run serializes the compiled
    executable; a fresh predictor loads it and matches outputs."""
    import paddle_tpu.nn as nn
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.jit import InputSpec, save as jit_save

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    prefix = str(tmp_path / "m")
    jit_save(net, prefix, input_spec=[InputSpec([4, 8], "float32")])

    xv = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    ref = np.asarray(net(paddle.to_tensor(xv))._value)

    cache = str(tmp_path / "engines")
    cfg = Config(prefix)
    cfg.set_optim_cache_dir(cache)
    p1 = create_predictor(cfg)
    out1 = p1.run([xv])
    np.testing.assert_allclose(out1[0], ref, rtol=1e-5, atol=1e-6)
    import os

    engines = [f for f in os.listdir(cache) if f.endswith(".pdexec")]
    assert len(engines) == 1

    # fresh predictor: loads the serialized engine (same file, no new)
    cfg2 = Config(prefix)
    cfg2.set_optim_cache_dir(cache)
    p2 = create_predictor(cfg2)
    out2 = p2.run([xv])
    np.testing.assert_allclose(out2[0], ref, rtol=1e-5, atol=1e-6)
    assert len(os.listdir(cache)) == 1
