"""Tensor basics (reference tests: unittests/test_var_base.py style)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def test_to_tensor_roundtrip():
    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    t = paddle.to_tensor(a)
    assert t.shape == [3, 4]
    assert t.dtype == paddle.float32
    np.testing.assert_array_equal(t.numpy(), a)


def test_dtype_conversion():
    t = paddle.to_tensor([1, 2, 3], dtype="int32")
    f = t.astype("float32")
    assert f.dtype == paddle.float32
    assert t.dtype == paddle.int32


def test_default_float64_downcast():
    t = paddle.to_tensor(np.zeros(3))  # float64 numpy
    assert t.dtype == paddle.float32


def test_arith_dunders():
    a = paddle.to_tensor([1.0, 2.0])
    b = paddle.to_tensor([3.0, 4.0])
    np.testing.assert_allclose((a + b).numpy(), [4, 6])
    np.testing.assert_allclose((a - b).numpy(), [-2, -2])
    np.testing.assert_allclose((a * b).numpy(), [3, 8])
    np.testing.assert_allclose((b / a).numpy(), [3, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4])
    np.testing.assert_allclose((2.0 * a).numpy(), [2, 4])
    np.testing.assert_allclose((1.0 - a).numpy(), [0, -1])
    np.testing.assert_allclose((-a).numpy(), [-1, -2])


def test_matmul_operator():
    a = paddle.to_tensor(np.eye(3, dtype=np.float32))
    b = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
    np.testing.assert_allclose((a @ b).numpy(), b.numpy())


def test_comparison():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([2.0, 2.0, 2.0])
    np.testing.assert_array_equal((a < b).numpy(), [True, False, False])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])


def test_getitem_setitem():
    t = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    row = t[1]
    np.testing.assert_allclose(row.numpy(), [4, 5, 6, 7])
    sub = t[0:2, 1:3]
    assert sub.shape == [2, 2]
    t[0, 0] = 99.0
    assert float(t[0, 0].item()) == 99.0
    # tensor fancy index
    idx = paddle.to_tensor([0, 2])
    picked = t[idx]
    assert picked.shape == [2, 4]


def test_item_and_scalars():
    t = paddle.to_tensor(3.5)
    assert t.item() == pytest.approx(3.5)
    assert float(t) == pytest.approx(3.5)
    assert int(paddle.to_tensor(7)) == 7


def test_detach_and_clone():
    t = paddle.to_tensor([1.0], stop_gradient=False)
    d = t.detach()
    assert d.stop_gradient
    c = t.clone()
    assert c.shape == [1]


def test_set_value():
    t = paddle.to_tensor([1.0, 2.0])
    t.set_value(np.asarray([5.0, 6.0], np.float32))
    np.testing.assert_allclose(t.numpy(), [5, 6])


def test_shape_props():
    t = paddle.to_tensor(np.zeros((2, 3, 4), np.float32))
    assert t.ndim == 3
    assert t.numel() == 24
    assert len(t) == 2
    assert t.T.shape == [4, 3, 2]


def test_save_load(tmp_path):
    path = str(tmp_path / "ckpt.pdparams")
    obj = {"w": paddle.to_tensor([1.0, 2.0]), "step": 3}
    paddle.save(obj, path)
    loaded = paddle.load(path)
    np.testing.assert_allclose(loaded["w"].numpy(), [1, 2])
    assert loaded["step"] == 3
