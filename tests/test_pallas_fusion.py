"""Fused Pallas kernel library (ISSUE 8): CPU interpret-mode parity.

Every kernel is validated against the unfused XLA composition it
replaces — forward AND gradients, f32 and bf16, odd shapes no real
TPU tiling would accept — and the fused multi-tensor optimizer update
is validated against the per-parameter apply_gradients loop it
replaces, across every supported rule and state shape.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
import paddle_tpu.optimizer as optim
from paddle_tpu.incubate.nn.pallas.layernorm import (
    fused_layer_norm, fused_residual_layer_norm)


def _ref_ln(x, w, b, eps=1e-5, act=None, approx=True):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, -1, keepdims=True)
    var = jnp.var(xf, -1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps) * w + b
    if act == "gelu":
        y = jax.nn.gelu(y, approximate=approx)
    return y.astype(x.dtype)


# ---------------------------------------------------------------------------
# fused layernorm (+gelu, +residual): forward + gradient parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shape", [(4, 7, 96), (3, 129)])
@pytest.mark.parametrize("dt", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("act", [None, "gelu"])
def test_fused_layer_norm_parity(shape, dt, act):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape), dt)
    w = jnp.asarray(rng.randn(shape[-1]), jnp.float32)
    b = jnp.asarray(rng.randn(shape[-1]), jnp.float32)
    y = fused_layer_norm(x, w, b, 1e-5, act, True, True)
    yr = _ref_ln(x, w, b, act=act)
    tol = 2e-6 if dt == jnp.float32 else 5e-2
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(yr, np.float32),
        rtol=tol, atol=tol)

    def f(x, w, b):
        return jnp.sum(jnp.sin(fused_layer_norm(
            x, w, b, 1e-5, act, True, True).astype(jnp.float32)))

    def fr(x, w, b):
        return jnp.sum(jnp.sin(_ref_ln(x, w, b, act=act)
                               .astype(jnp.float32)))

    g = jax.grad(f, (0, 1, 2))(x, w, b)
    gr = jax.grad(fr, (0, 1, 2))(x, w, b)
    gtol = 2e-4 if dt == jnp.float32 else 1.0
    for a, r, nm in zip(g, gr, "xwb"):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(r, np.float32),
            rtol=gtol, atol=gtol, err_msg=f"d{nm}")


def test_fused_layer_norm_erf_gelu():
    """approximate=False epilogue (erf gelu) has its own derivative."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(13, 40), jnp.float32)
    w = jnp.asarray(rng.randn(40), jnp.float32)
    b = jnp.asarray(rng.randn(40), jnp.float32)
    y = fused_layer_norm(x, w, b, 1e-5, "gelu", False, True)
    yr = _ref_ln(x, w, b, act="gelu", approx=False)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=2e-6, atol=2e-6)
    g = jax.grad(lambda x: jnp.sum(jnp.sin(fused_layer_norm(
        x, w, b, 1e-5, "gelu", False, True))))(x)
    gr = jax.grad(lambda x: jnp.sum(jnp.sin(_ref_ln(
        x, w, b, act="gelu", approx=False))))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                               rtol=2e-4, atol=2e-4)


def test_fused_residual_layer_norm_parity():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(5, 100), jnp.float32)
    r = jnp.asarray(rng.randn(5, 100), jnp.float32)
    w = jnp.asarray(rng.randn(100), jnp.float32)
    b = jnp.asarray(rng.randn(100), jnp.float32)
    y, s = fused_residual_layer_norm(x, r, w, b, 1e-5, None, True, True)
    # the sum output is the input-dtype addition, bit-exactly
    assert float(jnp.max(jnp.abs(s - (x + r)))) == 0.0
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(_ref_ln(x + r, w, b)),
                               rtol=2e-6, atol=2e-6)

    # BOTH outputs carry cotangents (y feeds the block, s the next
    # residual) — the backward must merge them
    def f(x, r, w, b):
        y, s = fused_residual_layer_norm(x, r, w, b, 1e-5, None, True,
                                         True)
        return jnp.sum(jnp.sin(y)) + jnp.sum(jnp.cos(s))

    def fr(x, r, w, b):
        s = x + r
        return jnp.sum(jnp.sin(_ref_ln(s, w, b))) + jnp.sum(jnp.cos(s))

    g = jax.grad(f, (0, 1, 2, 3))(x, r, w, b)
    gr = jax.grad(fr, (0, 1, 2, 3))(x, r, w, b)
    for a, rr, nm in zip(g, gr, ["x", "residual", "w", "b"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(rr),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{nm}")


# ---------------------------------------------------------------------------
# gates: off by default, fallback always safe
# ---------------------------------------------------------------------------

def test_fusion_off_by_default(monkeypatch):
    monkeypatch.delenv("PADDLE_PALLAS_FUSION", raising=False)
    from paddle_tpu.incubate.nn import pallas

    assert not pallas.fusion_enabled()
    assert not pallas.kernels_available()
    assert not pallas.ln_supported(1024)


def test_functional_wrapper_fused_matches_fallback(monkeypatch):
    """The Tensor-level incubate functional op must produce the same
    values fused (interpret kernels) and unfused (composition)."""
    from paddle_tpu.incubate.nn import functional as IF

    rng = np.random.RandomState(3)
    xv = rng.randn(2, 9, 48).astype(np.float32)
    rv = rng.randn(2, 9, 48).astype(np.float32)
    wv = rng.randn(48).astype(np.float32)
    bv = rng.randn(48).astype(np.float32)

    def run():
        x = paddle.to_tensor(xv)
        r = paddle.to_tensor(rv)
        w = paddle.to_tensor(wv)
        b = paddle.to_tensor(bv)
        y, s = IF.fused_residual_layer_norm(x, r, w, b, 1e-5)
        z = IF.fused_layer_norm_gelu(x, w, b, 1e-5)
        return np.asarray(y._value), np.asarray(s._value), \
            np.asarray(z._value)

    monkeypatch.delenv("PADDLE_PALLAS_FUSION", raising=False)
    y0, s0, z0 = run()
    monkeypatch.setenv("PADDLE_PALLAS_FUSION", "1")
    monkeypatch.setenv("PADDLE_PALLAS_INTERPRET", "1")
    y1, s1, z1 = run()
    np.testing.assert_allclose(y0, y1, rtol=2e-6, atol=2e-6)
    np.testing.assert_allclose(s0, s1, rtol=0, atol=0)
    np.testing.assert_allclose(z0, z1, rtol=2e-6, atol=2e-6)


# ---------------------------------------------------------------------------
# fused multi-tensor optimizer update vs the per-parameter loop
# ---------------------------------------------------------------------------

def _mk_params(rng, bf16=False):
    dt = np.float32
    params = {"w": jnp.asarray(rng.randn(130, 70), dt),
              "b": jnp.asarray(rng.randn(70), dt),
              "big": jnp.asarray(rng.randn(40000), dt)}
    if bf16:
        params = {n: v.astype(jnp.bfloat16) for n, v in params.items()}
    grads = {n: jnp.asarray(rng.randn(*np.shape(v)), v.dtype)
             for n, v in params.items()}
    return params, grads


def _compare_fused_vs_loop(make_opt, params, grads, steps=3, lr=0.01):
    opt_f, opt_p = make_opt(), make_opt()
    opt_p._pallas_fused_kind = None  # force the per-parameter loop
    st_f = opt_f.init_state(params)
    st_p = opt_p.init_state(params)
    pf, pp = dict(params), dict(params)
    for _ in range(steps):
        pf, st_f = opt_f.apply_gradients(pf, grads, st_f, lr)
        pp, st_p = opt_p.apply_gradients(pp, grads, st_p, lr)
    for n in pf:
        np.testing.assert_allclose(
            np.asarray(pf[n], np.float32), np.asarray(pp[n], np.float32),
            rtol=1e-6, atol=1e-6, err_msg=n)
    for n in st_f:
        for s in st_f[n]:
            np.testing.assert_allclose(
                np.asarray(st_f[n][s]), np.asarray(st_p[n][s]),
                rtol=1e-6, atol=1e-6, err_msg=f"{n}.{s}")


@pytest.fixture
def fusion_on(monkeypatch):
    monkeypatch.setenv("PADDLE_PALLAS_FUSION", "1")
    monkeypatch.setenv("PADDLE_PALLAS_INTERPRET", "1")


@pytest.mark.parametrize("mk", [
    lambda: optim.SGD(0.1),
    lambda: optim.Momentum(0.1, momentum=0.9),
    lambda: optim.Momentum(0.1, momentum=0.9, use_nesterov=True),
    lambda: optim.Adam(0.01),
    lambda: optim.Adam(0.01, weight_decay=0.02),       # coupled L2
    lambda: optim.AdamW(0.01, weight_decay=0.05),      # decoupled
    lambda: optim.AdamW(0.01, weight_decay=0.05,
                        apply_decay_param_fun=lambda n: n != "b"),
], ids=["sgd", "momentum", "nesterov", "adam", "adam_l2", "adamw",
        "adamw_filter"])
def test_fused_optimizer_matches_loop(fusion_on, mk):
    rng = np.random.RandomState(0)
    params, grads = _mk_params(rng)
    _compare_fused_vs_loop(mk, params, grads)


def test_fused_optimizer_master_weights(fusion_on):
    """multi_precision bf16 params: the fused kernel updates the fp32
    master and re-derives the half param, like the loop."""
    rng = np.random.RandomState(1)
    params, grads = _mk_params(rng, bf16=True)
    _compare_fused_vs_loop(
        lambda: optim.Adam(0.01, multi_precision=True), params, grads)


def test_fused_optimizer_none_grads_passthrough(fusion_on):
    """Params without a gradient pass through untouched (frozen legs
    of a partially trainable model)."""
    rng = np.random.RandomState(2)
    params, grads = _mk_params(rng)
    grads["b"] = None
    opt = optim.Adam(0.01)
    st = opt.init_state(params)
    new_p, new_st = opt.apply_gradients(params, grads, st, 0.01)
    assert new_p["b"] is params["b"]
    np.testing.assert_allclose(np.asarray(new_st["b"]["moment1"]), 0.0)
    assert not np.allclose(np.asarray(new_p["w"]),
                           np.asarray(params["w"]))


def test_fused_optimizer_inside_grad_clip(fusion_on):
    """Global-norm clip runs before the fused kernel, identically to
    the loop path."""
    import paddle_tpu.nn as nn

    rng = np.random.RandomState(3)
    params, grads = _mk_params(rng)
    clip = nn.ClipGradByGlobalNorm(0.01)
    _compare_fused_vs_loop(
        lambda: optim.Adam(0.01, grad_clip=clip), params, grads)


# ---------------------------------------------------------------------------
# end-to-end: compiled train step, fused vs unfused, same losses
# ---------------------------------------------------------------------------

def _gpt_losses(steps=2):
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=96, hidden_size=48, num_layers=1,
                    num_heads=4, ffn_hidden=96, max_seq_len=32,
                    dropout=0.0, remat=False, use_flash_attention=False)
    m = GPTForCausalLM(cfg)
    opt = optim.AdamW(learning_rate=1e-3, parameters=m.parameters(),
                      weight_decay=0.01)
    step = TrainStepCompiler(m, opt, loss_fn=None)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (2, 16)).astype(np.int32))
    return [float(step(ids, ids).item()) for _ in range(steps)]


def test_train_step_fused_matches_unfused(monkeypatch):
    """The whole donated program — fused LayerNorm kernels in the
    model AND the fused optimizer update — trains to the same losses
    as the unfused composition."""
    monkeypatch.delenv("PADDLE_PALLAS_FUSION", raising=False)
    base = _gpt_losses()
    monkeypatch.setenv("PADDLE_PALLAS_FUSION", "1")
    monkeypatch.setenv("PADDLE_PALLAS_INTERPRET", "1")
    fused = _gpt_losses()
    assert fused[-1] < fused[0]  # it actually trains
    np.testing.assert_allclose(fused, base, rtol=2e-5, atol=2e-5)


def test_fused_optimizer_zero_size_param(fusion_on):
    """A zero-element parameter occupies a whole (padded) chunk — the
    pack math must see its true size or the stacked buffer stops
    being a chunk multiple (review regression)."""
    rng = np.random.RandomState(4)
    params = {"w": jnp.asarray(rng.randn(33, 9), jnp.float32),
              "empty": jnp.zeros((0,), jnp.float32)}
    grads = {"w": jnp.asarray(rng.randn(33, 9), jnp.float32),
             "empty": jnp.zeros((0,), jnp.float32)}
    _compare_fused_vs_loop(lambda: optim.Adam(0.01), params, grads)


def test_auto_workers_env_clamped(monkeypatch):
    """PADDLE_IO_WORKERS=0 clamps to 1: auto-sizing always means SOME
    pool (bench feeds the value straight into MultiprocessLoader's
    round-robin divide); explicit num_workers=0 stays the
    single-process path."""
    from paddle_tpu.io import _auto_num_workers, _resolve_num_workers

    monkeypatch.setenv("PADDLE_IO_WORKERS", "0")
    assert _auto_num_workers() == 1
    assert _resolve_num_workers(-1) == 1
    assert _resolve_num_workers(0) == 0  # explicit stays explicit
    monkeypatch.setenv("PADDLE_IO_WORKERS", "5")
    assert _resolve_num_workers("auto") == 5
