"""Regression tests for the r4 advisor findings (ADVICE.md round 4)."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle


# -- dataplane: reconnect must not deadlock (advisor medium #1) ----------

def test_dataplane_reconnect_after_receiver_restart():
    from paddle_tpu.distributed.dataplane import DataPlane

    rx = DataPlane(host="127.0.0.1")
    tx = DataPlane(host="127.0.0.1")
    arr = np.arange(8, dtype=np.float32)
    tx.send(rx.endpoint, src=1, tag="t", seq=0, arr=arr)
    got = rx.recv(src=1, tag="t", seq=0, timeout=10)
    np.testing.assert_array_equal(got, arr)

    # receiver "restarts": old server goes away, a new one takes the
    # same port; the sender's cached connection is now dead
    port = rx.port
    rx.close()
    rx2 = DataPlane(host="127.0.0.1", port=port)

    done = {}

    def _send():
        tx.send(rx2.endpoint, src=1, tag="t", seq=1, arr=arr * 2)
        done["ok"] = True

    th = threading.Thread(target=_send, daemon=True)
    th.start()
    th.join(timeout=15)  # the old code deadlocked here forever
    assert done.get("ok"), "send deadlocked in the reconnect path"
    got = rx2.recv(src=1, tag="t", seq=1, timeout=10)
    np.testing.assert_array_equal(got, arr * 2)
    tx.close()
    rx2.close()


def test_dataplane_advertised_host_from_env(monkeypatch):
    from paddle_tpu.distributed.dataplane import _advertised_host

    monkeypatch.delenv("PADDLE_DATAPLANE_HOST", raising=False)
    monkeypatch.setenv("PADDLE_CURRENT_ENDPOINT", "10.1.2.3:6170")
    assert _advertised_host() == "10.1.2.3"
    monkeypatch.setenv("PADDLE_DATAPLANE_HOST", "10.9.9.9")
    assert _advertised_host() == "10.9.9.9"
    monkeypatch.delenv("PADDLE_DATAPLANE_HOST", raising=False)
    monkeypatch.delenv("PADDLE_CURRENT_ENDPOINT", raising=False)
    assert _advertised_host() == "127.0.0.1"


# -- dy2static: one-sided traced return must raise (advisor medium #2) ---

def test_one_sided_return_raises(tmp_path):
    from paddle_tpu.jit import to_static

    src = tmp_path / "mod_onesided.py"
    src.write_text(
        "import paddle_tpu as paddle\n"
        "def one_sided(x):\n"
        "    if paddle.mean(x) > 0:\n"
        "        return x * 2\n"
        "def tail_ret(x):\n"
        "    if paddle.mean(x) > 0:\n"
        "        return x * 2\n"
        "    return x * 3\n"
        "def nested_tail(x):\n"
        "    if paddle.mean(x) > 0:\n"
        "        if paddle.max(x) > 5:\n"
        "            return x * 4\n"
        "        return x * 2\n"
        "    return x * 3\n")
    import importlib.util

    spec = importlib.util.spec_from_file_location("mod_onesided", src)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    neg = paddle.to_tensor(-np.ones(3, np.float32))
    pos = paddle.to_tensor(np.ones(3, np.float32))
    big = paddle.to_tensor(np.full(3, 10.0, np.float32))

    with pytest.raises(ValueError, match="every path"):
        to_static(mod.one_sided)(neg)
    # legit early-return patterns keep working
    np.testing.assert_allclose(to_static(mod.tail_ret)(pos).numpy(),
                               2 * np.ones(3))
    np.testing.assert_allclose(to_static(mod.tail_ret)(neg).numpy(),
                               -3 * np.ones(3))
    f = to_static(mod.nested_tail)
    np.testing.assert_allclose(f(big).numpy(), 40 * np.ones(3))
    np.testing.assert_allclose(f(pos).numpy(), 2 * np.ones(3))
    np.testing.assert_allclose(f(neg).numpy(), -3 * np.ones(3))


# -- sparse: mixed sparse/dense binary ops (advisor low #4) --------------

def test_sparse_subtract_mixed_dense():
    import paddle_tpu.sparse as sparse

    dense = np.array([[0.0, 1.0], [2.0, 0.0]], np.float32)
    sp = sparse.to_sparse_coo(paddle.to_tensor(dense))
    other = np.array([[1.0, 1.0], [1.0, 1.0]], np.float32)
    ot = paddle.to_tensor(other)

    out = sparse.subtract(sp, ot)
    np.testing.assert_allclose(out.numpy(), dense - other)
    out2 = sparse.subtract(ot, sp)
    np.testing.assert_allclose(out2.numpy(), other - dense)


def test_sparse_multiply_dense_lhs():
    import paddle_tpu.sparse as sparse

    dense = np.array([[0.0, 2.0], [3.0, 0.0]], np.float32)
    sp = sparse.to_sparse_coo(paddle.to_tensor(dense))
    other = np.array([[5.0, 6.0], [7.0, 8.0]], np.float32)

    out = sparse.multiply(paddle.to_tensor(other), sp)
    np.testing.assert_allclose(out.to_dense().numpy(), dense * other)


def test_sparse_divide_dense_lhs_raises():
    import paddle_tpu.sparse as sparse

    dense = np.array([[0.0, 2.0], [3.0, 0.0]], np.float32)
    sp = sparse.to_sparse_coo(paddle.to_tensor(dense))
    with pytest.raises(TypeError, match="dividend must be sparse"):
        sparse.divide(paddle.to_tensor(np.ones((2, 2), np.float32)), sp)


# -- r5 zero-copy loader: raw-mode batch ownership -----------------------

from collections import namedtuple as _namedtuple

_NTBatch = _namedtuple("_NTBatch", ["x", "y"])


class _NTDS:
    def __len__(self):
        return 16

    def __getitem__(self, i):
        return np.full((4, 4), i, np.float32), np.int64(i)


def _nt_collate(samples):
    xs, ys = zip(*samples)
    return _NTBatch(np.stack(xs), np.stack(ys))


def test_raw_collate_preserves_types_and_owns_data():
    from paddle_tpu.io import DataLoader

    dl = DataLoader(_NTDS(), batch_size=4, num_workers=2,
                    use_shared_memory=True, collate_fn=_nt_collate)
    batches = list(dl)  # worker pool shuts down here (rings munmap)
    assert len(batches) == 4
    for b in batches:
        assert type(b).__name__ == "_NTBatch" and hasattr(b, "x")
        # every array must OWN its data: slot views after shutdown
        # would read unmapped memory
        assert b.x.base is None or b.x.flags.owndata
        first = int(b.y[0])
        np.testing.assert_allclose(b.x[0], np.full((4, 4), first))


def test_stable_bn_stats_flag():
    """FLAGS_stable_bn_stats switches BN training stats to the
    cancellation-free two-pass form (r4 advisor low #3): with a huge
    per-channel offset the default one-pass form floors variance to 0,
    the stable form recovers it."""
    import paddle_tpu.nn as nn
    from paddle_tpu.core import flags

    rng = np.random.RandomState(0)
    # |mean| >> std: mean 1e4, std 1e-1 — (1e4)^2 dwarfs var in f32
    x = (1e4 + 0.1 * rng.randn(16, 4, 8, 8)).astype(np.float32)

    prior = flags.get_flag("stable_bn_stats")

    def batch_var(stable):
        flags.set_flags({"stable_bn_stats": stable})
        try:
            bn = nn.BatchNorm2D(4)
            bn.train()
            bn(paddle.to_tensor(x))
            # running var after one step: momentum*1 + 0.1*unbiased
            return np.asarray(bn._variance._value)
        finally:
            flags.set_flags({"stable_bn_stats": prior})

    true_var = x.var(axis=(0, 2, 3))
    v_stable = (batch_var(True) - 0.9) / 0.1
    np.testing.assert_allclose(v_stable, true_var * (x[:, 0].size /
                               (x[:, 0].size - 1)), rtol=0.05)
    v_naive = (batch_var(False) - 0.9) / 0.1
    # the naive form is garbage in this domain (variance floored to 0
    # or blown up by cancellation noise) — demonstrate the documented
    # restriction is real
    rel_err = np.abs(v_naive - true_var) / true_var
    assert rel_err.max() > 0.5, rel_err
