"""Memory observability & OOM forensics (paddle_tpu.monitor.memory +
the paddle.device memory-stats API) — the HBM axis of the telemetry
stack: census accounting against known-size arrays, peak/reset
semantics, per-program memory_analysis in jit.cache_report(), a
simulated RESOURCE_EXHAUSTED leaving an "oom" bundle whose memory
section names the top live arrays, and the CLI memory/inspect
round-trip (including pre-memory-schema bundles)."""
import glob
import json
import os

import numpy as np
import pytest

import jax
import paddle_tpu as paddle
from paddle_tpu import device
from paddle_tpu.core import monitor as core_monitor
from paddle_tpu.monitor import flight, memory
from paddle_tpu.monitor.cli import main as cli_main
from jaxlib.xla_extension import XlaRuntimeError

OOM_MSG = ("RESOURCE_EXHAUSTED: Out of memory allocating "
           "1099511627776 bytes (simulated)")


@pytest.fixture(autouse=True)
def _clean(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_FLIGHT_DIR", str(tmp_path))
    flight.recorder.clear()
    yield
    flight.uninstall_excepthook()


# ---------------------------------------------------------------------------
# device stats + census accounting
# ---------------------------------------------------------------------------

def test_memory_allocated_accounts_known_array():
    base = device.memory_allocated()
    a = jax.device_put(np.ones((256, 1024), np.float32))  # 1 MiB
    try:
        assert device.memory_allocated() - base == a.nbytes == 2**20
    finally:
        del a


def test_memory_allocated_resolves_device_specifiers():
    """Reference-API specifiers (int ordinal, "platform:idx" string)
    must read the real device — not silently account 0 bytes against
    a bogus string-keyed watermark."""
    a = jax.device_put(np.ones((64, 64), np.float32))
    try:
        dev = jax.devices()[0]
        want = device.memory_allocated(dev)
        assert device.memory_allocated(0) == want
        assert device.memory_allocated(f"{dev.platform}:0") == want
        assert device.memory_allocated(dev.platform) == want
        with pytest.raises(TypeError):
            device.memory_allocated(True)
    finally:
        del a


def test_census_groups_by_shape_dtype():
    a = jax.device_put(np.ones((128, 64), np.float32))
    b = jax.device_put(np.ones((128, 64), np.float32))
    c = jax.device_put(np.ones((32,), np.int32))
    try:
        census = memory.live_array_census(top_k=0)
        groups = {(tuple(g["shape"]), g["dtype"]): g
                  for g in census["groups"]}
        g = groups[((128, 64), "float32")]
        assert g["count"] >= 2
        assert g["bytes"] >= a.nbytes + b.nbytes
        assert ((32,), "int32") in groups
        assert census["total_bytes"] >= sum(
            gr["bytes"] for gr in census["groups"]) or census["truncated"]
        # grouped report never carries array CONTENTS
        assert "values" not in json.dumps(census)
    finally:
        del a, b, c


def test_census_top_k_truncates_groups_not_totals():
    arrs = [jax.device_put(np.ones((i + 1, 7), np.float32))
            for i in range(5)]
    try:
        full = memory.live_array_census(top_k=0)
        cut = memory.live_array_census(top_k=2)
        assert len(cut["groups"]) <= 2
        assert cut["group_count"] == full["group_count"]
        assert cut["total_bytes"] == full["total_bytes"]
        assert cut["truncated"]
        # ranked by bytes descending
        sizes = [g["bytes"] for g in full["groups"]]
        assert sizes == sorted(sizes, reverse=True)
    finally:
        del arrs


def test_peak_and_reset_semantics():
    a = jax.device_put(np.ones((512, 512), np.float32))  # 1 MiB
    high = device.memory_allocated()
    assert device.max_memory_allocated() >= high
    del a
    low = device.memory_allocated()
    assert low < high
    assert device.max_memory_allocated() >= high  # peak survives free
    new_peak = device.reset_max_memory_allocated()
    assert new_peak == device.memory_allocated()
    assert device.max_memory_allocated() < high


def test_memory_stats_normalized_keys():
    stats = device.memory_stats()
    assert stats["source"] in ("pjrt", "census")
    assert stats["allocated_bytes"] >= 0
    assert stats["peak_bytes"] >= stats["allocated_bytes"]


def test_telemetry_snapshot_syncs_mem_gauges():
    from paddle_tpu import monitor

    a = jax.device_put(np.ones((64, 64), np.float32))
    try:
        snap = monitor.telemetry_snapshot()
        assert snap["stats"]["mem/allocated_bytes"] >= a.nbytes
        assert snap["stats"]["mem/peak_bytes"] >= \
            snap["stats"]["mem/allocated_bytes"] - 1
    finally:
        del a


# ---------------------------------------------------------------------------
# per-program footprints
# ---------------------------------------------------------------------------

def _tiny_step(model_cls=None):
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.jit import TrainStepCompiler

    paddle.seed(0)
    net = (model_cls or nn.Linear)(8, 4)
    ce = nn.CrossEntropyLoss()
    opt = optim.Adam(learning_rate=1e-3, parameters=net.parameters())
    step = TrainStepCompiler(net, opt, lambda o, y: ce(o, y))
    x = paddle.to_tensor(np.random.randn(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.randint(0, 4, (4,)).astype(np.int64))
    return step, x, y


def test_cache_report_exposes_train_step_memory():
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import cache_report

    # unique class name: gauge + cache_report fn are keyed by
    # type(model).__name__, and other suites also compile Linear steps
    class CacheReportLinear(nn.Linear):
        pass

    step, x, y = _tiny_step(CacheReportLinear)
    step(x, y)
    ent = next(e for e in cache_report()
               if e["kind"] == "train_step"
               and e["fn"] == "CacheReportLinear" and e.get("memory"))
    mem = ent["memory"]
    for key in ("argument_bytes", "output_bytes", "temp_bytes",
                "generated_code_bytes", "total_bytes"):
        assert isinstance(mem[key], int), key
    assert mem["argument_bytes"] > 0  # params + batch are real bytes
    assert core_monitor.stat_get(
        "mem/program/train_step:CacheReportLinear/argument_bytes") \
        == mem["argument_bytes"]


def test_cache_report_exposes_to_static_memory():
    from paddle_tpu.jit import cache_report, to_static

    @to_static
    def poly(v):
        return v * v + v

    poly(paddle.to_tensor(np.ones((16, 16), np.float32)))
    ent = next(e for e in cache_report()
               if e["kind"] == "to_static"
               and e["fn"].split(".")[-1] == "poly")
    assert len(ent["memory"]) == len(ent["keys"])
    mem = ent["memory"][0]
    assert mem and mem["argument_bytes"] >= 16 * 16 * 4


def test_to_static_multi_entry_gauges_not_overwritten():
    """Shape-specialized cache entries of one to_static fn keep
    distinct mem/program gauges — a small tail-batch compile must not
    overwrite the full-batch footprint (last-writer-wins)."""
    from paddle_tpu.jit import to_static

    @to_static
    def poly_entries(v):
        return v * v

    poly_entries(paddle.to_tensor(np.ones((64, 64), np.float32)))
    poly_entries(paddle.to_tensor(np.ones((4, 4), np.float32)))
    fname = poly_entries._telemetry_key
    big = core_monitor.stat_get(f"mem/program/{fname}/argument_bytes")
    small = core_monitor.stat_get(
        f"mem/program/{fname}#1/argument_bytes")
    assert big >= 64 * 64 * 4  # entry 0 (full batch) survives
    assert 0 < small < big  # tail entry landed on its own gauge


def test_program_capture_env_off(monkeypatch):
    from paddle_tpu.jit import cache_report, to_static

    monkeypatch.setenv("PADDLE_MEM_PROGRAM", "0")

    @to_static
    def poly_off(v):
        return v + 1

    poly_off(paddle.to_tensor(np.ones((4, 4), np.float32)))
    ent = next(e for e in cache_report()
               if e["kind"] == "to_static"
               and e["fn"].split(".")[-1] == "poly_off")
    assert ent["memory"] == [None]


def test_program_footprints_sibling_compilers_both_kept():
    """Two live train-step compilers over one model class (the fused
    + tail sibling shape) must not overwrite each other in
    program_footprints()."""
    import gc

    gc.collect()  # drop dead compilers other tests leaked
    base = [n for n in memory.program_footprints()
            if n.startswith("train_step:Linear")]
    step1, x, y = _tiny_step()
    step1(x, y)
    step2, x2, y2 = _tiny_step()
    step2(x2, y2)
    names = [n for n in memory.program_footprints()
             if n.startswith("train_step:Linear")]
    # baseline-relative: earlier suites may hold live Linear
    # compilers of their own — only OUR two must both appear
    assert len(names) == len(base) + 2, (base, names)


def test_cli_inspect_multi_entry_to_static_shows_largest(capsys):
    from paddle_tpu.jit import to_static

    @to_static
    def poly2(v):
        return v * v

    poly2(paddle.to_tensor(np.ones((4, 4), np.float32)))
    poly2(paddle.to_tensor(np.ones((64, 64), np.float32)))  # larger
    path = flight.write_dump("sigusr1")
    assert cli_main(["inspect", path]) == 0
    out = capsys.readouterr().out
    line = next(ln for ln in out.splitlines()
                if "to_static:" in ln and "poly2" in ln)
    assert "largest of 2 entries" in line
    assert "arg=16.0KiB" in line  # the 64x64 entry, not the 4x4 one


def test_cost_model_memory_cost_and_cache():
    from paddle_tpu.cost_model import CostModel

    cm = CostModel()

    def f(a, b):
        return a @ b

    x = jax.numpy.ones((64, 64))
    mc = cm.memory_cost(f, x, x)
    assert mc["argument_bytes"] == 2 * 64 * 64 * 4
    assert mc["total_bytes"] > 0
    cm.static_cost(f, x, x)
    cm.profile_measure(f, x, x, warmup=1, iters=2)
    assert len(cm._cache) == 1  # one compile served all three probes
    cm.memory_cost(f, jax.numpy.ones((32, 64)), x)
    assert len(cm._cache) == 2  # new signature, new entry


def test_cost_model_program_cost_reuses_compile():
    """Repeated program_cost probes of one program reuse ONE replay
    closure (and therefore one compiled executable) — a planner loop
    must not pin a fresh executable per call."""
    import paddle_tpu.static as static
    from paddle_tpu.cost_model import CostModel

    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [8, 16], "float32")
            y = paddle.matmul(x, paddle.to_tensor(
                np.ones((16, 4), np.float32)))
            paddle.nn.functional.relu(y)
        cm = CostModel()
        feed = {"x": np.ones((8, 16), np.float32)}
        cm.program_cost(main, feed)
        cm.program_cost(main, feed)
        assert len(cm._prog_fns) == 1
        assert len(cm._cache) == 1  # second probe was a cache hit
    finally:
        paddle.disable_static()


def test_cost_model_program_cost_evicts_stale_versions():
    """A mutated program (version bump) must not leave the previous
    version's replay closure and compiled executable pinned — the
    planner loop probe/pass/probe pattern would otherwise leak one
    executable per pass iteration."""
    import paddle_tpu.static as static
    from paddle_tpu.cost_model import CostModel

    paddle.enable_static()
    try:
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [8, 16], "float32")
            y = paddle.matmul(x, paddle.to_tensor(
                np.ones((16, 4), np.float32)))
            paddle.nn.functional.relu(y)
        cm = CostModel()
        feed = {"x": np.ones((8, 16), np.float32)}
        cm.program_cost(main, feed)
        main._version = getattr(main, "_version", 0) + 1
        cm.program_cost(main, feed)
        assert len(cm._prog_fns) == 1  # stale version evicted
        assert len(cm._cache) == 1  # and its executable with it
    finally:
        paddle.disable_static()


# ---------------------------------------------------------------------------
# step-boundary tracking + chrome-trace counters
# ---------------------------------------------------------------------------

def test_step_timer_records_mem_gauges_and_counters(tmp_path):
    from paddle_tpu import monitor, profiler

    keep = jax.device_put(np.ones((128, 128), np.float32))
    try:
        st = monitor.StepTimer()
        prof = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU])
        with prof:
            for _ in range(2):
                st.begin_step()
                st.end_step(batch_size=4)
        assert core_monitor.stat_get("step/mem/allocated_bytes") \
            >= keep.nbytes
        assert core_monitor.stat_get("step/mem/peak_bytes") >= \
            core_monitor.stat_get("step/mem/allocated_bytes")
        trace = tmp_path / "trace_rank0.json"
        prof.export(str(trace))
        evs = json.load(open(trace))["traceEvents"]
        mem_evs = [e for e in evs if e.get("ph") == "C"
                   and e.get("name") == "mem/allocated_bytes"]
        assert mem_evs and all(
            e["args"]["value"] >= keep.nbytes for e in mem_evs)
        # merge-traces keeps the counter series (the Perfetto memory
        # timeline the acceptance criteria names)
        merged = tmp_path / "merged.json"
        assert cli_main(["merge-traces", "-o", str(merged),
                         str(trace)]) == 0
        mevs = json.load(open(merged))["traceEvents"]
        assert any(e.get("ph") == "C"
                   and e.get("name") == "mem/allocated_bytes"
                   for e in mevs)
    finally:
        del keep


def test_step_timer_mem_tracking_env_off(monkeypatch):
    from paddle_tpu import monitor

    monkeypatch.setenv("PADDLE_MEM_STEP", "0")
    core_monitor.stat_reset("step/mem/allocated_bytes")
    st = monitor.StepTimer()
    st.begin_step()
    st.end_step(batch_size=1)
    assert core_monitor.stat_get("step/mem/allocated_bytes") == 0


def test_profiler_step_mem_env_off(tmp_path, monkeypatch):
    """PADDLE_MEM_STEP=0 covers Profiler.step too — same knob, same
    census-walk cost being opted out of."""
    from paddle_tpu import profiler

    monkeypatch.setenv("PADDLE_MEM_STEP", "0")
    keep = jax.device_put(np.ones((64, 64), np.float32))
    try:
        prof = profiler.Profiler(
            targets=[profiler.ProfilerTarget.CPU])
        with prof:
            prof.step(num_samples=4)
        trace = tmp_path / "t.json"
        prof.export(str(trace))
        evs = json.load(open(trace))["traceEvents"]
        assert not [e for e in evs if e.get("ph") == "C"
                    and e.get("name") == "mem/allocated_bytes"]
    finally:
        del keep


# ---------------------------------------------------------------------------
# OOM classification + forensics bundles
# ---------------------------------------------------------------------------

def test_is_oom_error_classification():
    assert memory.is_oom_error(XlaRuntimeError(OOM_MSG))
    assert not memory.is_oom_error(XlaRuntimeError("INTERNAL: boom"))
    assert not memory.is_oom_error(ValueError(OOM_MSG))
    assert not memory.is_oom_error(None)


def test_oom_observer_writes_bundle_with_census(tmp_path):
    held = jax.device_put(np.ones((333, 333), np.float32))
    try:
        with pytest.raises(XlaRuntimeError):
            with memory.oom_observer():
                raise XlaRuntimeError(OOM_MSG)
        paths = glob.glob(str(tmp_path / "oom_*.json"))
        assert len(paths) == 1
        bundle = json.load(open(paths[0]))
        assert bundle["reason"] == "oom"
        assert bundle["exception"]["type"] == "XlaRuntimeError"
        mem = bundle["memory"]
        assert mem["device"]["allocated_bytes"] >= held.nbytes
        assert any(tuple(g["shape"]) == (333, 333)
                   for g in mem["census"]["groups"])
        # per-program footprints ride along (dict, possibly empty)
        assert isinstance(mem["programs"], dict)
        # inspect renders the memory section
        assert cli_main(["inspect", paths[0]]) == 0
    finally:
        del held


def test_excepthook_classifies_oom_reason(tmp_path):
    flight.install_excepthook()
    flight._flight_excepthook(XlaRuntimeError,
                              XlaRuntimeError(OOM_MSG), None)
    assert glob.glob(str(tmp_path / "oom_*.json"))
    assert not glob.glob(str(tmp_path / "crash_*.json"))


def test_excepthook_skips_already_dumped_oom(tmp_path):
    """oom_observer bundles first (census while arrays live); the
    excepthook must not shadow it with a second dump."""
    flight.install_excepthook()
    exc = XlaRuntimeError(OOM_MSG)
    with pytest.raises(XlaRuntimeError):
        with memory.oom_observer():
            raise exc
    flight._flight_excepthook(XlaRuntimeError, exc, None)
    assert len(glob.glob(str(tmp_path / "*_rank*_pid*.json"))) == 1


def test_oom_observer_custom_reason_keeps_census(tmp_path):
    """oom_observer(reason=...) exists to be renamed — the bundle
    must keep the census regardless of the reason string."""
    with pytest.raises(XlaRuntimeError):
        with memory.oom_observer(reason="train_oom"):
            raise XlaRuntimeError(OOM_MSG)
    paths = glob.glob(str(tmp_path / "train_oom_*.json"))
    assert len(paths) == 1
    assert "census" in json.load(open(paths[0]))["memory"]


def test_crash_bundle_carries_light_memory_section(tmp_path):
    """Non-OOM bundles get device stats + program footprints but no
    census (cheap evidence on every dump)."""
    path = flight.write_dump("crash")
    bundle = json.load(open(path))
    mem = bundle["memory"]
    assert "device" in mem and "programs" in mem
    assert "census" not in mem


def test_fit_oom_leaves_bundle(tmp_path, monkeypatch):
    """Model.fit auto-arms oom_observer: a RESOURCE_EXHAUSTED inside
    the train loop leaves an oom bundle and re-raises."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.hapi import Model

    paddle.seed(0)
    net = nn.Linear(4, 2)
    m = Model(net)
    m.prepare(optimizer=optim.SGD(learning_rate=0.1,
                                  parameters=net.parameters()),
              loss=nn.CrossEntropyLoss())
    monkeypatch.setattr(
        Model, "_train_batch_tail",
        lambda self, ins, lbls: (_ for _ in ()).throw(
            XlaRuntimeError(OOM_MSG)))
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randint(0, 2, (8,)).astype(np.int64)
    ds = [(x[i], y[i]) for i in range(8)]
    with pytest.raises(XlaRuntimeError):
        m.fit(ds, batch_size=4, epochs=1, verbose=0)
    paths = glob.glob(str(tmp_path / "oom_*.json"))
    assert len(paths) == 1
    assert "census" in json.load(open(paths[0]))["memory"]


def test_fit_oom_observer_respects_autoarm_off(tmp_path, monkeypatch):
    """PADDLE_FLIGHT_AUTOARM=0 (the flight opt-out maybe_auto_arm
    honors) also disarms fit's OOM observer — no bundle, exception
    still propagates."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.hapi import Model

    monkeypatch.setenv("PADDLE_FLIGHT_AUTOARM", "0")
    paddle.seed(0)
    net = nn.Linear(4, 2)
    m = Model(net)
    m.prepare(optimizer=optim.SGD(learning_rate=0.1,
                                  parameters=net.parameters()),
              loss=nn.CrossEntropyLoss())
    monkeypatch.setattr(
        Model, "_train_batch_tail",
        lambda self, ins, lbls: (_ for _ in ()).throw(
            XlaRuntimeError(OOM_MSG)))
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randint(0, 2, (8,)).astype(np.int64)
    ds = [(x[i], y[i]) for i in range(8)]
    with pytest.raises(XlaRuntimeError):
        m.fit(ds, batch_size=4, epochs=1, verbose=0)
    assert not glob.glob(str(tmp_path / "oom_*.json"))


# ---------------------------------------------------------------------------
# CLI round-trips
# ---------------------------------------------------------------------------

def test_cli_memory_reports_live_process(capsys):
    held = jax.device_put(np.ones((77, 11), np.float32))
    try:
        assert cli_main(["memory"]) == 0
        out = capsys.readouterr().out
        assert "live arrays" in out and "77x11" in out
        assert cli_main(["memory", "--json", "--top", "3"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["device"]["source"] in ("pjrt", "census")
        assert len(rep["census"]["groups"]) <= 3
    finally:
        del held


def test_cli_inspect_tolerates_pre_memory_bundle(tmp_path, capsys):
    """Bundles written before the memory section existed (same
    paddle_tpu.flight/1 schema, key absent) still inspect cleanly."""
    bundle = {"schema": "paddle_tpu.flight/1", "reason": "crash",
              "ts": 1700000000.0, "rank": 0, "world_size": 1,
              "pid": 1234, "host": "h", "argv": [],
              "env": {}, "device": {}, "in_flight": [],
              "threads": [], "flight_tail": [],
              "telemetry": {"stats": {}}, "jit_caches": []}
    p = tmp_path / "crash_rank0_pid1234_1.json"
    with open(p, "w") as f:
        json.dump(bundle, f)
    assert cli_main(["inspect", str(p)]) == 0
    out = capsys.readouterr().out
    assert "flight dump: crash" in out
    assert "memory" not in out.splitlines()[-1]  # no phantom section


def test_cli_inspect_renders_program_memory(tmp_path, capsys):
    from paddle_tpu.jit import cache_report

    step, x, y = _tiny_step()
    step(x, y)
    path = flight.write_dump("sigusr1")
    assert cli_main(["inspect", str(path)]) == 0
    out = capsys.readouterr().out
    assert "memory (" in out
    assert "train_step" in out
    # acceptance: the bundle names per-program temp/argument bytes
    bundle = json.load(open(path))
    mems = [c.get("memory") for c in bundle["jit_caches"]
            if c["kind"] == "train_step"]
    assert any(m and m.get("argument_bytes", 0) > 0 for m in mems)
    assert cache_report()  # still intact after dump


# ---------------------------------------------------------------------------
# device.Event satellite
# ---------------------------------------------------------------------------

def test_event_untimed_does_not_sync_and_errors(monkeypatch):
    calls = []
    monkeypatch.setattr(device, "synchronize",
                        lambda *a, **k: calls.append(1))
    ev = device.Event()  # enable_timing defaults False
    ev.record()
    assert calls == []  # no hard sync for an ordering-only event
    assert ev.query()
    end = device.Event()
    end.record()
    with pytest.raises(RuntimeError, match="enable_timing"):
        ev.elapsed_time(end)


def test_event_timed_measures(monkeypatch):
    calls = []
    monkeypatch.setattr(device, "synchronize",
                        lambda *a, **k: calls.append(1))
    a = device.Event(enable_timing=True)
    b = device.Event(enable_timing=True)
    a.record()
    b.record()
    assert len(calls) == 2  # timed events DO drain the device
    assert a.elapsed_time(b) >= 0.0


def test_event_mixed_timing_errors():
    a = device.Event(enable_timing=True)
    a.record()
    b = device.Event(enable_timing=False)
    b.record()
    with pytest.raises(RuntimeError):
        a.elapsed_time(b)
