"""Flash-attention Pallas kernel correctness (interpret mode on CPU).

Parity target: fused attention numerics
(/root/reference/paddle/fluid/operators/fused/fmha_ref.h). The kernels
are validated against the dense softmax-attention reference for both
forward and all three gradients, causal and non-causal.
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.incubate.nn.attention_pallas import (
    _attn_ref, flash_attention)

ON_TPU = any(d.platform in ("tpu", "axon") for d in jax.devices())


def _rand_qkv(b=1, h=2, s=256, d=64, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.5
    k = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.5
    v = jnp.asarray(rng.randn(b, h, s, d), jnp.float32) * 0.5
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_dense(causal):
    q, k, v = _rand_qkv()
    scale = 1.0 / np.sqrt(q.shape[-1])
    out = flash_attention(q, k, v, causal, scale, 128, 128, True)
    _, ref = _attn_ref(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_dense(causal):
    q, k, v = _rand_qkv(s=256)
    scale = 1.0 / np.sqrt(q.shape[-1])

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, scale, 128, 128,
                                       True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attn_ref(q, k, v, causal, scale)[1] ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")


def test_flash_uneven_blocks():
    # seq 384 with 128-blocks: 3 kv blocks, partial diagonal coverage
    q, k, v = _rand_qkv(s=384, d=64, seed=3)
    scale = 0.125
    out = flash_attention(q, k, v, True, scale, 128, 128, True)
    _, ref = _attn_ref(q, k, v, True, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_block_q_ne_block_k():
    q, k, v = _rand_qkv(s=512, seed=4)
    scale = 0.125
    out = flash_attention(q, k, v, True, scale, 256, 128, True)
    _, ref = _attn_ref(q, k, v, True, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.skipif(not ON_TPU, reason="long-seq memory test needs TPU")
def test_flash_long_sequence_8k():
    """seq=8192: dense attention would materialize a 8k x 8k f32 score
    matrix per head (256 MB x heads); flash streams KV tiles and must
    run fwd+bwd within VMEM/HBM budget."""
    q, k, v = _rand_qkv(b=1, h=4, s=8192, d=64)
    q = q.astype(jnp.bfloat16)
    k = k.astype(jnp.bfloat16)
    v = v.astype(jnp.bfloat16)
    scale = 0.125

    def f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, scale).astype(
            jnp.float32))

    loss, grads = jax.jit(jax.value_and_grad(f, argnums=(0, 1, 2)))(q, k, v)
    assert np.isfinite(float(loss))
    for g in grads:
        assert bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))


# ---------------------------------------------------------------------------
# divisor-free sequence lengths: padded, masked, bit-exact on the
# unpadded region (ISSUE 8 satellite — _pick_block used to hard-raise)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("s", [7, 129])
def test_flash_padded_sequence_matches_dense(causal, s):
    """Lengths with no power-of-two block divisor pad up inside the
    wrapper; padded KV positions are masked to exactly zero weight and
    padded q rows sliced off."""
    q, k, v = _rand_qkv(s=s, d=16, seed=7)
    scale = 1.0 / np.sqrt(q.shape[-1])
    out = flash_attention(q, k, v, causal, scale, 1024, 1024, True)
    _, ref = _attn_ref(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_padded_sequence_grads():
    q, k, v = _rand_qkv(s=129, d=16, seed=8)
    scale = 0.25

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, scale, 1024, 1024,
                                       True) ** 2)

    def f_ref(q, k, v):
        return jnp.sum(_attn_ref(q, k, v, True, scale)[1] ** 2)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"d{name} mismatch")


def test_flash_padded_cross_attention():
    # sq != sk, neither divisible: both sides pad independently
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(1, 2, 129, 16), jnp.float32) * 0.5
    k = jnp.asarray(rng.randn(1, 2, 72, 16), jnp.float32) * 0.5
    v = jnp.asarray(rng.randn(1, 2, 72, 16), jnp.float32) * 0.5
    out = flash_attention(q, k, v, False, 0.25, 1024, 1024, True)
    _, ref = _attn_ref(q, k, v, False, 0.25)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_block_and_pad_prefers_divisors():
    from paddle_tpu.incubate.nn.attention_pallas import _block_and_pad

    assert _block_and_pad(1024, 1024) == (1024, 1024)  # exact
    assert _block_and_pad(384, 1024) == (128, 384)     # divisor path
    assert _block_and_pad(129, 1024) == (128, 256)     # padded
    assert _block_and_pad(7, 1024) == (8, 8)           # tiny
