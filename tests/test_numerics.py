"""ISSUE 17 — PTA09x precision sanitizer: static low-precision hazard
analysis + the runtime numerics probe.

Each static detector (PTA090/091/092/094/095) is proven against a
seeded hazard AND its clean twin; two historical-bug redos gate the
anchors (the bf16-accumulation and fp16-eps-underflow classes must
name the offending eqn/literal, not just the program). The runtime
half: PTA093 aborts a master-weightless fp16 build under
`PADDLE_SANITIZE=numerics`, the fused stats probe attributes an
injected fp16 overflow to the offending tensor (findings + flight
dump bundle), GradScaler backoff/growth annotate the flight timeline,
and DISARMED the lowering is bit-identical with zero numerics
counters — the same zero-overhead contract every family carries.
Plus: spec grammar (`numerics:sample=N:absmax=T`), CLI `--sanitize
numerics` AST leg, the amp list audit, and the PTA-code doc-drift
gate against the README table.
"""
import json
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import analysis, nn, optimizer as optim
from paddle_tpu.analysis import precision
from paddle_tpu.core.monitor import registry
from paddle_tpu.jit import InputSpec
from paddle_tpu.monitor import numerics as num
from paddle_tpu.monitor import sanitize as san

THIS_FILE = __file__


@pytest.fixture(autouse=True)
def _clean_numerics():
    yield
    san.disarm()
    san.clear_findings()
    num.clear()


def _codes(report):
    return {f.code for f in report.findings}


def _only(report, code):
    hits = [f for f in report.findings if f.code == code]
    assert hits, f"expected {code}, got {report.findings}"
    return hits[0]


def _assert_anchored_here(finding):
    assert finding.file == THIS_FILE, finding
    assert isinstance(finding.line, int) and finding.line > 0, finding
    assert f"{THIS_FILE}:{finding.line}" in finding.format()


# ---------------------------------------------------------------------------
# PTA090 — half-precision accumulation (historical-bug redo: the
# finding must name the offending dot eqn, anchored at the call site)
# ---------------------------------------------------------------------------

def test_pta090_bf16_accumulation_flagged():
    def f(x):
        return x @ x  # bf16 matmul, no f32 accumulator asked for

    rep = analysis.check(f, input_spec=[InputSpec([8, 8], "bfloat16")],
                         record=False)
    find = _only(rep, "PTA090")
    assert find.severity == "warning"
    assert "preferred_element_type" in find.message
    _assert_anchored_here(find)


def test_pta090_silent_with_f32_accumulator():
    import jax
    import jax.numpy as jnp

    def f(x):
        return jax.lax.dot_general(
            x._value, x._value, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    rep = analysis.check(f, input_spec=[InputSpec([8, 8], "bfloat16")],
                         record=False)
    assert "PTA090" not in _codes(rep)


# ---------------------------------------------------------------------------
# PTA091 — wide half-precision reductions (raw lax: jnp.sum upcasts)
# ---------------------------------------------------------------------------

def _raw_reduce(x):
    import jax

    return jax.lax.reduce_sum_p.bind(x._value, axes=(0,))


def test_pta091_wide_half_reduce_flagged():
    rep = analysis.check(_raw_reduce,
                         input_spec=[InputSpec([8192], "float16")],
                         record=False)
    find = _only(rep, "PTA091")
    assert "8192" in find.message and "float16" in find.message


def test_pta091_silent_below_threshold():
    rep = analysis.check(_raw_reduce,
                         input_spec=[InputSpec([128], "float16")],
                         record=False)
    assert "PTA091" not in _codes(rep)


# ---------------------------------------------------------------------------
# PTA092 — exp-family statistics in float16 (bf16 has f32's exponent
# range, so it is exempt by design)
# ---------------------------------------------------------------------------

def _exp_prog(x):
    import jax.numpy as jnp

    return jnp.exp(x._value)


def test_pta092_fp16_exp_flagged():
    rep = analysis.check(_exp_prog,
                         input_spec=[InputSpec([16], "float16")],
                         record=False)
    find = _only(rep, "PTA092")
    assert find.severity == "error"


def test_pta092_bf16_exp_clean():
    rep = analysis.check(_exp_prog,
                         input_spec=[InputSpec([16], "bfloat16")],
                         record=False)
    assert "PTA092" not in _codes(rep)


# ---------------------------------------------------------------------------
# PTA094 — the `1e-12` LayerNorm-eps-in-fp16 class (historical-bug
# redo: jax flushes the literal at trace time; the detector must still
# name the offending add, anchored in THIS file)
# ---------------------------------------------------------------------------

def test_pta094_fp16_eps_underflow_flagged():
    import jax.numpy as jnp

    def f(x):
        v = x._value
        return v / jnp.sqrt(jnp.var(v) + jnp.float16(1e-12))

    rep = analysis.check(f, input_spec=[InputSpec([32], "float16")],
                         record=False)
    find = _only(rep, "PTA094")
    assert find.severity == "error"
    assert "zero" in find.message
    _assert_anchored_here(find)


def test_pta094_silent_with_representable_eps():
    import jax.numpy as jnp

    def f(x):
        v = x._value
        return v / jnp.sqrt(jnp.var(v) + jnp.float16(1e-4))

    rep = analysis.check(f, input_spec=[InputSpec([32], "float16")],
                         record=False)
    assert "PTA094" not in _codes(rep)


# ---------------------------------------------------------------------------
# PTA095 — cast churn
# ---------------------------------------------------------------------------

def test_pta095_round_trip_cast_flagged():
    import jax.numpy as jnp

    def f(x):
        return x._value.astype(jnp.bfloat16).astype(jnp.float32)

    rep = analysis.check(f, input_spec=[InputSpec([8], "float32")],
                         record=False)
    find = _only(rep, "PTA095")
    assert "float32->bfloat16->float32" in find.message


def test_pta095_single_cast_clean():
    import jax.numpy as jnp

    def f(x):
        return x._value.astype(jnp.bfloat16)

    rep = analysis.check(f, input_spec=[InputSpec([8], "float32")],
                         record=False)
    assert "PTA095" not in _codes(rep)


# ---------------------------------------------------------------------------
# PTA093 — master-weightless fp16 training (build-time audit)
# ---------------------------------------------------------------------------

def _fp16_setup():
    model = nn.Linear(4, 2)
    paddle.amp.decorate(model, level="O2", dtype="float16")
    opt = optim.SGD(learning_rate=0.1,
                    parameters=model.parameters())
    x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float16))
    y = paddle.to_tensor(np.zeros((4,), dtype="int64"))
    return model, opt, x, y


def test_pta093_masterless_fp16_build_aborts_armed():
    san.configure("numerics")
    model, opt, x, y = _fp16_setup()
    step = paddle.jit.TrainStepCompiler(model, opt,
                                        nn.CrossEntropyLoss())
    with pytest.raises(ValueError) as ei:
        step(x, y)
    msg = str(ei.value)
    assert "PTA093" in msg and "float16" in msg and "weight" in msg
    assert "PTA093" in {f.code for f in san.findings()}


def test_pta093_grad_scaler_is_the_clean_twin():
    san.configure("numerics")
    model, opt, x, y = _fp16_setup()
    step = paddle.jit.TrainStepCompiler(
        model, opt, nn.CrossEntropyLoss(),
        grad_scaler=paddle.amp.GradScaler(init_loss_scaling=1.0))
    step(x, y)  # builds and runs — no PTA093
    assert "PTA093" not in {f.code for f in san.findings()}


def test_pta093_multi_precision_is_the_other_clean_twin():
    san.configure("numerics")
    assert not precision.audit_train_precision(
        {"w": "float16"}, None, True)
    # bf16 is exempt by design (f32 exponent range)
    assert not precision.audit_train_precision(
        {"w": "bfloat16"}, None, False)


def test_pta093_disarmed_is_silent_and_counter_clean():
    assert not san.armed()
    before = {k: v for k, v in registry.snapshot().items()
              if k.startswith(("sanitize/", "analysis/PTA09"))}
    assert not precision.audit_train_precision(
        {"w": "float16"}, None, False)
    after = {k: v for k, v in registry.snapshot().items()
             if k.startswith(("sanitize/", "analysis/PTA09"))}
    assert after == before


# ---------------------------------------------------------------------------
# PTA092 — auto_cast white-list audit (armed raises, bf16 exempt)
# ---------------------------------------------------------------------------

def test_autocast_fp16_whitelisting_softmax_raises_armed():
    san.configure("numerics")
    with pytest.raises(ValueError) as ei:
        with paddle.amp.auto_cast(dtype="float16",
                                  custom_white_list=["softmax"]):
            pass
    assert "PTA092" in str(ei.value) and "softmax" in str(ei.value)


def test_autocast_bf16_whitelist_clean():
    san.configure("numerics")
    with paddle.amp.auto_cast(dtype="bfloat16",
                              custom_white_list=["softmax"]):
        pass
    assert "PTA092" not in {f.code for f in san.findings()}


# ---------------------------------------------------------------------------
# runtime numerics probe — overflow attribution + dump bundle
# ---------------------------------------------------------------------------

def test_probe_attributes_fp16_overflow_to_tensor(tmp_path,
                                                  monkeypatch):
    import jax.numpy as jnp

    from paddle_tpu.monitor import flight

    monkeypatch.setenv("PADDLE_FLIGHT_DIR", str(tmp_path))
    san.configure("numerics")
    model, opt, x, y = _fp16_setup()
    # inject the overflow: a weight near fp16 max saturates the
    # matmul and blows the grads to inf
    model.weight._value = jnp.full(tuple(model.weight.shape),
                                   60000.0, jnp.float16)
    step = paddle.jit.TrainStepCompiler(
        model, opt, nn.CrossEntropyLoss(),
        grad_scaler=paddle.amp.GradScaler(init_loss_scaling=1.0))
    step(x, y)
    msgs = [f.message for f in san.findings() if f.code == "PTA092"]
    assert any("param/weight" in m for m in msgs), msgs
    snap = registry.snapshot()
    assert snap.get("numerics/param/weight/saturated", 0) >= 1 \
        or snap.get("numerics/param/weight/nonfinite", 0) >= 1
    assert any(k.startswith("numerics/") and k.endswith("/absmax")
               for k in snap)
    # the dump bundle carries the probe's last-read stats, so a
    # post-mortem names the tensor
    path = flight.write_dump("numerics_probe")
    with open(path) as f:
        payload = json.load(f)
    assert payload["numerics"]["armed"] is True
    assert "param/weight" in payload["numerics"]["last"]
    kinds = [e["kind"] for e in flight.recorder.tail(256)]
    assert "sanitize_finding" in kinds


def test_grad_scaler_backoff_annotates_flight_timeline():
    from paddle_tpu.monitor import flight

    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0,
                                   decr_every_n_nan_or_inf=1)
    scaler._record_step(True)  # one non-finite microstep verdict
    kinds = [e["kind"] for e in flight.recorder.tail(64)]
    assert "amp_scale_backoff" in kinds
    assert scaler.get_init_loss_scaling() == 512.0


def test_probe_scan_path_and_sample_cadence():
    san.configure("numerics:sample=2")
    assert num.sample_every() == 2
    model = nn.Linear(4, 2)
    opt = optim.SGD(learning_rate=0.1,
                    parameters=model.parameters())
    step = paddle.jit.TrainStepCompiler(model, opt,
                                        nn.CrossEntropyLoss(),
                                        steps_per_dispatch=2)
    x = paddle.to_tensor(
        np.random.rand(2, 4, 4).astype(np.float32))
    y = paddle.to_tensor(np.zeros((2, 4), dtype="int64"))
    losses = step(x, y)
    assert tuple(losses.shape) == (2,)
    step(x, y)
    d = num.describe()
    # every dispatch observes; the sample=2 cadence bounds host syncs
    assert d["observations"] == 2 and d["sample"] == 2
    assert any(k.startswith("param/") for k in d["last"])


# ---------------------------------------------------------------------------
# disarmed contract — bit-identical lowering, zero counters
# ---------------------------------------------------------------------------

def _zeroed_step():
    import jax.numpy as jnp

    model = nn.Linear(4, 2)
    for p in model.parameters():
        p._value = jnp.zeros_like(p._value)
    opt = optim.SGD(learning_rate=0.1,
                    parameters=model.parameters())
    return paddle.jit.TrainStepCompiler(model, opt,
                                        nn.CrossEntropyLoss())


def test_disarmed_lowering_bit_identical():
    x = paddle.to_tensor(np.ones((4, 4), np.float32))
    y = paddle.to_tensor(np.zeros((4,), dtype="int64"))
    plain1 = _zeroed_step().lower_compiled(x, y).as_text()
    plain2 = _zeroed_step().lower_compiled(x, y).as_text()
    assert plain1 == plain2  # deterministic baseline, probe-free
    san.configure("numerics")
    armed = _zeroed_step().lower_compiled(x, y).as_text()
    assert armed != plain1  # the probe only exists when armed


def test_disarmed_dispatch_zero_numerics_counters():
    assert not san.armed()
    step = _zeroed_step()
    x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
    y = paddle.to_tensor(np.zeros((4,), dtype="int64"))
    before = {k: v for k, v in registry.snapshot().items()
              if k.startswith("numerics/")}
    step(x, y)
    step(x, y)
    after = {k: v for k, v in registry.snapshot().items()
             if k.startswith("numerics/")}
    assert after == before
    assert step._numerics_built is False
    assert num.describe()["observations"] == 0


# ---------------------------------------------------------------------------
# spec grammar + CLI
# ---------------------------------------------------------------------------

def test_parse_spec_numerics_params():
    fams = san.parse_spec("numerics:sample=4:absmax=30000")
    assert fams == {"numerics": {"sample": 4.0, "absmax": 30000.0}}
    san.configure("numerics:absmax=30000")
    assert num.absmax_threshold() == 30000.0


def test_parse_spec_unknown_family_names_the_valid_ones():
    with pytest.raises(ValueError) as ei:
        san.parse_spec("numericz")
    msg = str(ei.value)
    assert "numericz" in msg and "numerics" in msg \
        and "donation" in msg


def test_numerics_env_params(monkeypatch):
    monkeypatch.setenv("PADDLE_NUMERICS_SAMPLE", "8")
    monkeypatch.setenv("PADDLE_NUMERICS_ABSMAX", "20000")
    san.configure("numerics")
    assert num.sample_every() == 8
    assert num.absmax_threshold() == 20000.0
    # the spec param wins over the env
    san.configure("numerics:sample=3")
    assert num.sample_every() == 3


def test_cli_sanitize_numerics_flags_seeded_file(tmp_path, capsys):
    from paddle_tpu.analysis.cli import main

    p = tmp_path / "m.py"
    p.write_text(
        "def norm_fp16(x, jnp):\n"
        "    h = x.astype('float16')\n"
        "    return rms(h, eps=1e-12)\n"
        "with auto_cast(dtype='float16',\n"
        "               custom_white_list=['softmax']):\n"
        "    pass\n")
    rc = main([str(p), "--sanitize", "numerics"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "PTA094" in out and "PTA092" in out
    # clean twin: f32 function with the same eps stays silent
    p.write_text("def norm(x):\n    return rms(x, eps=1e-12)\n")
    rc = main([str(p), "--sanitize", "numerics"])
    capsys.readouterr()
    assert rc == 0


def test_lint_numerics_source_direct():
    rep = precision.lint_numerics_source(
        "def f(x):\n"
        "    y = x.astype('float16')\n"
        "    return norm(y, epsilon=5e-9)\n", "t.py")
    find = _only(rep, "PTA094")
    assert find.line == 3
    # no fp16 mention -> the package's f32 eps defaults stay clean
    rep = precision.lint_numerics_source(
        "def f(x):\n    return norm(x, epsilon=5e-9)\n", "t.py")
    assert not rep.findings


# ---------------------------------------------------------------------------
# amp list audit — every entry must resolve against the live registry
# ---------------------------------------------------------------------------

def test_amp_lists_resolve_against_live_op_registry():
    stale = paddle.amp.audit_op_lists()
    assert stale == {"white": [], "black": []}, stale


def test_amp_white_list_has_no_predispatch_aliases():
    # mm/bmm delegate to matmul BEFORE dispatch — listing them would
    # be dead weight the audit exists to catch
    assert "mm" not in paddle.amp.WHITE_LIST
    assert "bmm" not in paddle.amp.WHITE_LIST
    assert "matmul" in paddle.amp.WHITE_LIST


# ---------------------------------------------------------------------------
# doc-drift gate — every registered PTA code has a README table row
# ---------------------------------------------------------------------------

def test_readme_documents_every_pta_code():
    import os

    from paddle_tpu.analysis.diagnostics import DIAGNOSTICS

    readme = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "README.md")
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    rows = set(re.findall(r"^\|\s*`?(PTA\d{3})`?\s*\|", text, re.M))
    codes = set(DIAGNOSTICS)
    assert codes - rows == set(), \
        f"codes missing a README table row: {sorted(codes - rows)}"
    assert rows - codes == set(), \
        f"README rows for unregistered codes: {sorted(rows - codes)}"
    for code in ("PTA090", "PTA091", "PTA092", "PTA093", "PTA094",
                 "PTA095"):
        assert code in codes
