"""Broad op-surface sweep (reference: the 2,134-file unittest corpus
validating all registered ops through op_test.py — here one
declarative table drives eager-vs-numpy output checks, finite-diff
gradient checks for differentiable ops, and an f32+bf16 dtype sweep
for a representative subset)."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core import engine

RNG = np.random.RandomState(7)
X34 = RNG.randn(3, 4).astype(np.float32)
POS34 = (np.abs(X34) + 0.5).astype(np.float32)
Y34 = RNG.randn(3, 4).astype(np.float32)
UNIT34 = np.clip(X34, -0.9, 0.9)
X234 = RNG.randn(2, 3, 4).astype(np.float32)
I34 = RNG.randint(0, 5, (3, 4)).astype(np.int32)


def erf_np(x):
    from scipy.special import erf as _erf  # scipy is available via jax deps

    return _erf(x)


try:
    import scipy  # noqa: F401

    HAVE_SCIPY = True
except ImportError:
    HAVE_SCIPY = False

# (name, args, kwargs, numpy reference, grad_check)
UNARY_CASES = [
    ("exp", (X34,), {}, np.exp, True),
    ("log", (POS34,), {}, np.log, True),
    ("log2", (POS34,), {}, np.log2, True),
    ("log10", (POS34,), {}, np.log10, True),
    ("log1p", (POS34,), {}, np.log1p, True),
    ("expm1", (X34,), {}, np.expm1, True),
    ("sqrt", (POS34,), {}, np.sqrt, True),
    ("rsqrt", (POS34,), {}, lambda x: 1 / np.sqrt(x), True),
    ("abs", (X34,), {}, np.abs, False),
    ("floor", (X34,), {}, np.floor, False),
    ("ceil", (X34,), {}, np.ceil, False),
    ("round", (X34,), {}, np.round, False),
    ("sign", (X34,), {}, np.sign, False),
    ("sin", (X34,), {}, np.sin, True),
    ("cos", (X34,), {}, np.cos, True),
    ("tan", (UNIT34,), {}, np.tan, True),
    ("asin", (UNIT34,), {}, np.arcsin, True),
    ("acos", (UNIT34,), {}, np.arccos, True),
    ("atan", (X34,), {}, np.arctan, True),
    ("sinh", (X34,), {}, np.sinh, True),
    ("cosh", (X34,), {}, np.cosh, True),
    ("tanh", (X34,), {}, np.tanh, True),
    ("asinh", (X34,), {}, np.arcsinh, True),
    ("acosh", (POS34 + 1,), {}, np.arccosh, True),
    ("atanh", (UNIT34 * 0.9,), {}, np.arctanh, True),
    ("square", (X34,), {}, np.square, True),
    ("reciprocal", (POS34,), {}, lambda x: 1 / x, True),
    ("sigmoid", (X34,), {}, lambda x: 1 / (1 + np.exp(-x)), True),
    ("digamma", (POS34 + 1,), {}, None, False),
    ("lgamma", (POS34 + 1,), {}, None, False),
    ("erf", (X34,), {},
     (lambda x: erf_np(x)) if HAVE_SCIPY else None, True),
    ("trunc", (X34 * 3,), {}, np.trunc, False),
    ("frac", (X34 * 3,), {}, lambda x: x - np.trunc(x), False),
    ("neg", (X34,), {}, np.negative, True),
    ("logit", (np.clip(POS34 / 4, 0.05, 0.95),), {},
     lambda x: np.log(x / (1 - x)), True),
]

BINARY_CASES = [
    ("add", lambda a, b: a + b),
    ("subtract", lambda a, b: a - b),
    ("multiply", lambda a, b: a * b),
    ("divide", lambda a, b: a / b),
    ("maximum", np.maximum),
    ("minimum", np.minimum),
    ("pow", None),  # handled specially (positive base)
    ("fmax", np.fmax),
    ("fmin", np.fmin),
    ("atan2", np.arctan2),
]

REDUCTION_CASES = [
    ("sum", {}, lambda x: np.sum(x)),
    ("mean", {}, lambda x: np.mean(x)),
    ("max", {}, lambda x: np.max(x)),
    ("min", {}, lambda x: np.min(x)),
    ("prod", {}, lambda x: np.prod(x)),
    ("sum", {"axis": 1}, lambda x: np.sum(x, axis=1)),
    ("mean", {"axis": 0}, lambda x: np.mean(x, axis=0)),
    ("std", {}, lambda x: np.std(x, ddof=1)),
    ("var", {}, lambda x: np.var(x, ddof=1)),
    ("logsumexp", {}, lambda x: np.log(np.sum(np.exp(x)))),
    ("amax", {"axis": 1}, lambda x: np.max(x, axis=1)),
    ("amin", {"axis": 1}, lambda x: np.min(x, axis=1)),
]

ACTIVATION_CASES = [
    ("relu", lambda x: np.maximum(x, 0)),
    ("relu6", lambda x: np.clip(x, 0, 6)),
    ("elu", lambda x: np.where(x > 0, x, np.exp(x) - 1)),
    ("silu", lambda x: x / (1 + np.exp(-x))),
    ("softplus", lambda x: np.log1p(np.exp(x))),
    ("softsign", lambda x: x / (1 + np.abs(x))),
    ("hardswish",
     lambda x: x * np.clip(x + 3, 0, 6) / 6),
    ("hardsigmoid", None),
    ("leaky_relu", lambda x: np.where(x > 0, x, 0.01 * x)),
    ("mish", None),
    ("gelu", None),
    ("selu", None),
    ("tanhshrink", lambda x: x - np.tanh(x)),
    ("softshrink", None),
    ("hardshrink", None),
    ("hardtanh", lambda x: np.clip(x, -1, 1)),
]

LOGIC_CASES = [
    ("equal", lambda a, b: a == b),
    ("not_equal", lambda a, b: a != b),
    ("greater_than", lambda a, b: a > b),
    ("greater_equal", lambda a, b: a >= b),
    ("less_than", lambda a, b: a < b),
    ("less_equal", lambda a, b: a <= b),
]


@pytest.mark.parametrize(
    "name,args,kwargs,ref,gradcheck", UNARY_CASES,
    ids=[f"{c[0]}" for c in UNARY_CASES])
def test_unary_op(name, args, kwargs, ref, gradcheck):
    op = getattr(paddle, name)
    out = op(*[paddle.to_tensor(a) for a in args], **kwargs)
    if ref is not None:
        np.testing.assert_allclose(
            np.asarray(out._value), ref(*args), rtol=2e-5, atol=2e-5)
    else:
        assert np.isfinite(np.asarray(out._value)).all()
    if gradcheck:
        _grad_check(op, args, kwargs)


def _grad_check(op, args, kwargs, eps=1e-3, rtol=2e-2, atol=2e-3):
    t = paddle.to_tensor(args[0], stop_gradient=False)
    rest = [paddle.to_tensor(a) for a in args[1:]]
    out = op(t, *rest, **kwargs)
    paddle.sum(out).backward()
    analytic = np.asarray(t.grad._value, np.float64)

    x = np.asarray(args[0], np.float64)
    num = np.zeros_like(x)
    flat, nflat = x.reshape(-1), num.reshape(-1)
    for i in range(flat.size):
        xp, xm = flat.copy(), flat.copy()
        xp[i] += eps
        xm[i] -= eps

        def f(v):
            with engine.no_grad():
                o = op(paddle.to_tensor(
                    v.reshape(x.shape).astype(np.float32)),
                    *rest, **kwargs)
            return float(np.asarray(o._value, np.float64).sum())

        nflat[i] = (f(xp) - f(xm)) / (2 * eps)
    np.testing.assert_allclose(analytic, num, rtol=rtol, atol=atol)


@pytest.mark.parametrize("name,ref", BINARY_CASES,
                         ids=[c[0] for c in BINARY_CASES])
def test_binary_op(name, ref):
    op = getattr(paddle, name)
    a, b = X34, np.abs(Y34) + 0.5
    if name == "pow":
        base = POS34
        out = op(paddle.to_tensor(base), paddle.to_tensor(b))
        np.testing.assert_allclose(np.asarray(out._value),
                                   np.power(base, b), rtol=1e-4)
        return
    out = op(paddle.to_tensor(a), paddle.to_tensor(b))
    np.testing.assert_allclose(np.asarray(out._value), ref(a, b),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("name,kwargs,ref", REDUCTION_CASES,
                         ids=[f"{c[0]}-{c[1]}" for c in REDUCTION_CASES])
def test_reduction_op(name, kwargs, ref):
    op = getattr(paddle, name)
    out = op(paddle.to_tensor(X34), **kwargs)
    np.testing.assert_allclose(np.asarray(out._value), ref(X34),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("name,ref", ACTIVATION_CASES,
                         ids=[c[0] for c in ACTIVATION_CASES])
def test_activation_op(name, ref):
    op = getattr(F, name)
    out = op(paddle.to_tensor(X34))
    if ref is not None:
        np.testing.assert_allclose(np.asarray(out._value), ref(X34),
                                   rtol=2e-5, atol=2e-5)
    else:
        assert np.asarray(out._value).shape == X34.shape
    # activations must be differentiable end-to-end
    t = paddle.to_tensor(X34, stop_gradient=False)
    paddle.sum(op(t)).backward()
    assert np.isfinite(np.asarray(t.grad._value)).all()


@pytest.mark.parametrize("name,ref", LOGIC_CASES,
                         ids=[c[0] for c in LOGIC_CASES])
def test_logic_op(name, ref):
    op = getattr(paddle, name)
    out = op(paddle.to_tensor(X34), paddle.to_tensor(Y34))
    np.testing.assert_array_equal(np.asarray(out._value), ref(X34, Y34))


def test_manipulation_ops_sweep():
    t = paddle.to_tensor(X234)
    np.testing.assert_array_equal(
        np.asarray(paddle.reshape(t, [4, 6])._value), X234.reshape(4, 6))
    np.testing.assert_array_equal(
        np.asarray(paddle.transpose(t, [1, 0, 2])._value),
        X234.transpose(1, 0, 2))
    np.testing.assert_array_equal(
        np.asarray(paddle.flip(t, axis=1)._value), X234[:, ::-1])
    np.testing.assert_array_equal(
        np.asarray(paddle.roll(t, 1, axis=0)._value),
        np.roll(X234, 1, axis=0))
    np.testing.assert_array_equal(
        np.asarray(paddle.squeeze(paddle.unsqueeze(t, 0), 0)._value),
        X234)
    parts = paddle.split(t, 3, axis=1)
    assert len(parts) == 3
    np.testing.assert_array_equal(
        np.asarray(paddle.concat(parts, axis=1)._value), X234)
    st = paddle.stack([t, t], axis=0)
    assert list(st.shape) == [2, 2, 3, 4]
    a, b = paddle.unstack(st, axis=0)
    np.testing.assert_array_equal(np.asarray(a._value), X234)
    np.testing.assert_array_equal(
        np.asarray(paddle.tile(paddle.to_tensor(X34), [2, 1])._value),
        np.tile(X34, (2, 1)))
    np.testing.assert_array_equal(
        np.asarray(paddle.clip(paddle.to_tensor(X34), -0.5, 0.5)._value),
        np.clip(X34, -0.5, 0.5))
    np.testing.assert_array_equal(
        np.asarray(paddle.cast(paddle.to_tensor(I34), "float32")._value),
        I34.astype(np.float32))


def test_search_ops_sweep():
    t = paddle.to_tensor(X34)
    np.testing.assert_array_equal(
        np.asarray(paddle.argmax(t, axis=1)._value),
        np.argmax(X34, axis=1))
    np.testing.assert_array_equal(
        np.asarray(paddle.argmin(t, axis=0)._value),
        np.argmin(X34, axis=0))
    vals, idx = paddle.topk(t, k=2, axis=1)
    ref = np.sort(X34, axis=1)[:, ::-1][:, :2]
    np.testing.assert_allclose(np.asarray(vals._value), ref, rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(paddle.sort(t, axis=1)._value), np.sort(X34, axis=1))
    np.testing.assert_array_equal(
        np.asarray(paddle.argsort(t, axis=1)._value),
        np.argsort(X34, axis=1))
    w = paddle.where(t > 0, t, paddle.zeros_like(t))
    np.testing.assert_array_equal(np.asarray(w._value),
                                  np.where(X34 > 0, X34, 0))
    np.testing.assert_array_equal(
        np.asarray(paddle.masked_select(t, t > 0)._value),
        X34[X34 > 0])


def test_linalg_ops_sweep():
    a = RNG.randn(3, 4).astype(np.float32)
    b = RNG.randn(4, 5).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(paddle.matmul(paddle.to_tensor(a),
                                 paddle.to_tensor(b))._value),
        a @ b, rtol=1e-5, atol=1e-5)
    v = RNG.randn(4).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(paddle.dot(paddle.to_tensor(v),
                              paddle.to_tensor(v))._value),
        v @ v, rtol=1e-5)
    np.testing.assert_allclose(
        float(paddle.linalg.norm(paddle.to_tensor(a)).item()),
        np.linalg.norm(a), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(paddle.t(paddle.to_tensor(a))._value), a.T)
    np.testing.assert_allclose(
        np.asarray(paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                                 paddle.to_tensor(b))._value),
        a @ b, rtol=1e-5, atol=1e-5)


def test_creation_ops_sweep():
    assert np.asarray(paddle.zeros([2, 3])._value).sum() == 0
    assert np.asarray(paddle.ones([2, 3])._value).sum() == 6
    np.testing.assert_array_equal(
        np.asarray(paddle.full([2, 2], 7.0)._value), np.full((2, 2), 7.0))
    np.testing.assert_array_equal(
        np.asarray(paddle.arange(0, 10, 2)._value), np.arange(0, 10, 2))
    np.testing.assert_allclose(
        np.asarray(paddle.linspace(0, 1, 5)._value),
        np.linspace(0, 1, 5), rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(paddle.eye(3)._value), np.eye(3, dtype=np.float32))
    np.testing.assert_array_equal(
        np.asarray(paddle.diag(paddle.to_tensor(
            np.array([1.0, 2.0], np.float32)))._value),
        np.diag([1.0, 2.0]))


# -- dtype sweep over a representative subset (bf16 thresholds) -------------

BF16_SWEEP = ["exp", "tanh", "sigmoid", "sqrt", "square", "abs"]


@pytest.mark.parametrize("name", BF16_SWEEP)
def test_bf16_dtype_sweep(name):
    import jax.numpy as jnp

    op = getattr(paddle, name)
    x = POS34
    ref = np.asarray(op(paddle.to_tensor(x))._value, np.float64)
    xb = paddle.to_tensor(jnp.asarray(x).astype(jnp.bfloat16))
    got = np.asarray(op(xb)._value).astype(np.float64)
    np.testing.assert_allclose(got, ref, rtol=2e-2, atol=2e-2)
    # bf16 grads flow and are finite
    t = paddle.to_tensor(jnp.asarray(x).astype(jnp.bfloat16),
                         stop_gradient=False)
    paddle.sum(op(t)).backward()
    assert np.isfinite(np.asarray(t.grad._value,
                                  np.float32)).all()


def test_cumulative_ops():
    t = paddle.to_tensor(X34)
    np.testing.assert_allclose(
        np.asarray(paddle.cumsum(t, axis=1)._value),
        np.cumsum(X34, axis=1), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(paddle.cumprod(t, dim=1)._value),
        np.cumprod(X34, axis=1), rtol=1e-4, atol=1e-5)
    vals, idx = paddle.cummax(t, axis=1)
    np.testing.assert_allclose(np.asarray(vals._value),
                               np.maximum.accumulate(X34, axis=1))


def test_indexing_and_padding_ops():
    t = paddle.to_tensor(X34)
    np.testing.assert_array_equal(
        np.asarray(paddle.gather(t, paddle.to_tensor(
            np.array([2, 0], np.int64)), axis=0)._value), X34[[2, 0]])
    np.testing.assert_array_equal(
        np.asarray(paddle.index_select(t, paddle.to_tensor(
            np.array([1, 3], np.int64)), axis=1)._value), X34[:, [1, 3]])
    oh = paddle.nn.functional.one_hot(
        paddle.to_tensor(np.array([0, 2], np.int64)), 4)
    np.testing.assert_array_equal(np.asarray(oh._value),
                                  np.eye(4, dtype=np.float32)[[0, 2]])
    padded = paddle.nn.functional.pad(t, [1, 1, 0, 0])
    # paddle pads FIRST-dim-first: [1,1,0,0] on (3,4) -> (5,4)
    assert list(padded.shape) == [5, 4]
    np.testing.assert_array_equal(
        np.asarray(paddle.broadcast_to(
            paddle.to_tensor(np.ones((1, 4), np.float32)),
            [3, 4])._value), np.ones((3, 4)))


def test_set_ops_and_uniques():
    v = paddle.to_tensor(np.array([3, 1, 3, 2, 1], np.int64))
    u = paddle.unique(v)
    got = np.sort(np.asarray((u[0] if isinstance(u, (tuple, list))
                              else u)._value))
    np.testing.assert_array_equal(got, [1, 2, 3])
    b = paddle.bincount(paddle.to_tensor(
        np.array([0, 1, 1, 3], np.int64)))
    np.testing.assert_array_equal(np.asarray(b._value), [1, 2, 0, 1])


def test_linalg_extras():
    a = RNG.randn(3, 3).astype(np.float32)
    np.testing.assert_allclose(
        float(paddle.trace(paddle.to_tensor(a)).item()),
        np.trace(a), rtol=1e-5)
    v1 = np.array([1.0, 0.0, 0.0], np.float32)
    v2 = np.array([0.0, 1.0, 0.0], np.float32)
    np.testing.assert_allclose(
        np.asarray(paddle.cross(paddle.to_tensor(v1),
                                paddle.to_tensor(v2))._value),
        np.cross(v1, v2), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(paddle.kron(paddle.to_tensor(np.eye(
            2, dtype=np.float32)), paddle.to_tensor(
                np.ones((2, 2), np.float32)))._value),
        np.kron(np.eye(2), np.ones((2, 2))), atol=1e-6)
    spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    L = np.asarray(paddle.linalg.cholesky(
        paddle.to_tensor(spd))._value)
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)


def test_stat_and_misc_ops():
    t = paddle.to_tensor(X34)
    np.testing.assert_allclose(
        float(paddle.median(paddle.to_tensor(
            np.array([3.0, 1.0, 2.0], np.float32))).item()), 2.0)
    np.testing.assert_allclose(
        np.asarray(paddle.quantile(t, 0.5)._value),
        np.quantile(X34, 0.5), rtol=1e-5)
    k = paddle.kthvalue(paddle.to_tensor(
        np.array([5.0, 1.0, 3.0], np.float32)), 2)
    vals = k[0] if isinstance(k, (tuple, list)) else k
    assert abs(float(np.asarray(vals._value)) - 3.0) < 1e-6
    np.testing.assert_allclose(
        np.asarray(paddle.diff(paddle.to_tensor(
            np.array([1.0, 4.0, 9.0], np.float32)))._value),
        [3.0, 5.0], atol=1e-6)
    mg = paddle.meshgrid(paddle.to_tensor(np.arange(2.0)),
                         paddle.to_tensor(np.arange(3.0)))
    assert list(mg[0].shape) == [2, 3]
