"""ISSUE 14: quantized collectives with error feedback
(paddle_tpu.distributed.compress).

Gates: blockwise kernel parity (jnp reference vs Pallas interpret),
quantized-allreduce math (+ error feedback) in shard_map, the
8-device e2e train gate (int8:ef wire_bytes <= 0.3x the explicit
fp32 twin, final-loss parity, PADDLE_COMM_COMPRESS unset bit-
identical to the implicit GSPMD program and comm-counter-clean),
bit-identical EF-residual checkpoint resume, the comm_compress chaos
site (raise + bitflip, disarmed provably clean), the PTA08x
sanitizer family (runtime + static, zero-overhead disarmed), the
list-arg collective payload fix, and the README doc-drift gate."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
from paddle_tpu.core import monitor as cmon
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import build_mesh, set_mesh
from paddle_tpu.distributed import collective as C
from paddle_tpu.distributed import compress as comp
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.jit.distributed import DistributedTrainStepCompiler
from paddle_tpu.monitor import chaos
from paddle_tpu.monitor import sanitize as msan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def mesh8():
    prev = mesh_mod.get_mesh()
    mesh = build_mesh({"dp": 8})
    set_mesh(mesh)
    yield mesh
    set_mesh(prev)


def _delta(keys):
    before = {k: cmon.stat_get(k) for k in keys}

    def read():
        return {k: cmon.stat_get(k) - before[k] for k in keys}

    return read


# ---------------------------------------------------------------------------
# config / spec grammar
# ---------------------------------------------------------------------------

def test_spec_grammar():
    cfg = comp.parse_spec("int8:ef:block=256")
    assert (cfg.mode, cfg.ef, cfg.block) == ("int8", True, 256)
    assert comp.parse_spec("fp8").spec() == "fp8"
    assert comp.parse_spec("off") is None and comp.parse_spec("") is None
    assert comp.resolve(None) is None and comp.resolve(False) is None
    assert comp.resolve(cfg) is cfg
    with pytest.raises(ValueError):
        comp.parse_spec("int4")
    with pytest.raises(ValueError):
        comp.parse_spec("int8:bogus=1")
    with pytest.raises(ValueError):
        comp.parse_spec("fp32:ef")  # EF corrects quant error; fp32 has none
    with pytest.raises(ValueError):
        comp.parse_spec("int8:block=100")  # not a 128-multiple


def test_bad_env_spec_is_loud_but_nonfatal(monkeypatch):
    monkeypatch.setenv("PADDLE_COMM_COMPRESS", "int5")
    assert comp.from_env() is None
    assert cmon.stat_get("comm/compress/spec_errors") >= 1


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def test_quantize_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 512).astype(np.float32) * 5)
    for mode, rel in (("int8", 1 / 127), ("fp8", 1 / 8)):
        q, s = comp.kernels.quantize_ref(x, 128, mode)
        assert q.dtype == comp.kernels.wire_dtype(mode)
        d = comp.kernels.dequantize_ref(q, s, 128, mode)
        # per-block bound: |x - deq| <= rel * blockwise absmax
        xb = np.asarray(x).reshape(-1, 128)
        db = np.asarray(d).reshape(-1, 128)
        bound = rel * np.abs(xb).max(axis=1, keepdims=True) + 1e-7
        assert (np.abs(xb - db) <= bound).all(), mode


def test_quantize_zero_block_is_exact():
    x = jnp.zeros((256,), jnp.float32)
    for mode in ("int8", "fp8"):
        q, s = comp.kernels.quantize_ref(x, 128, mode)
        d = comp.kernels.dequantize_ref(q, s, 128, mode)
        assert np.asarray(d).max() == 0.0 and np.asarray(s).min() == 1.0


def test_quantize_rejects_non_block_multiple():
    with pytest.raises(ValueError):
        comp.kernels.quantize_ref(jnp.zeros((100,)), 128, "int8")


def test_effective_block_clamps_tiny_payloads():
    """Found driving a 676-param model at the default 1024 block:
    padding to W*block made the 'compressed' wire LARGER than the
    fp32 one. The effective block clamps to one rank's 128-rounded
    segment, bounding padding; large payloads keep cfg.block."""
    cfg = comp.parse_spec("int8")  # default block 1024
    assert comp.effective_block(cfg, 676, 8) == 128
    assert comp.padded_elems(cfg, 676, 8) == 1024
    assert comp.wire_bytes_of(cfg, 1024, block=128) < 676 * 4
    # large payloads: cfg.block wins
    assert comp.effective_block(cfg, 1 << 20, 8) == 1024
    # the compiled tiny-model step really puts fewer bytes on the
    # wire than its fp32 logical payload
    paddle.seed(0)
    mesh = build_mesh({"dp": 8})
    set_mesh(mesh)
    try:
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 4))
        opt = optim.SGD(learning_rate=0.1,
                        parameters=model.parameters())
        step = DistributedTrainStepCompiler(model, opt, loss_fn=_mse,
                                            mesh=mesh,
                                            comm_compress="int8:ef")
        read = _delta(_COMM_KEYS)
        rng = np.random.RandomState(0)
        step(paddle.to_tensor(rng.randn(16, 16).astype(np.float32)),
             paddle.to_tensor(rng.randn(16, 4).astype(np.float32)))
        d = read()
        assert 0 < d["comm/all_reduce/wire_bytes"] < \
            d["comm/all_reduce/bytes"], d
    finally:
        set_mesh(None)


def test_pallas_int8_kernels_interpret_parity(monkeypatch):
    """The Pallas quant/dequant kernels (PADDLE_PALLAS_FUSION=1,
    interpret mode on CPU) are bit-identical to the jnp reference."""
    monkeypatch.setenv("PADDLE_PALLAS_FUSION", "1")
    monkeypatch.setenv("PADDLE_PALLAS_INTERPRET", "1")
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 1024).astype(np.float32) * 3)
    q_ref, s_ref = comp.kernels.quantize_ref(x, 256, "int8")
    q_k, s_k = comp.kernels.quantize_blocks(x, 256, "int8")
    np.testing.assert_array_equal(np.asarray(q_k), np.asarray(q_ref))
    np.testing.assert_array_equal(np.asarray(s_k), np.asarray(s_ref))
    d_ref = comp.kernels.dequantize_ref(q_ref, s_ref, 256, "int8")
    d_k = comp.kernels.dequantize_blocks(q_k, s_k, 256, "int8")
    np.testing.assert_array_equal(np.asarray(d_k), np.asarray(d_ref))


# ---------------------------------------------------------------------------
# quantized allreduce in shard_map
# ---------------------------------------------------------------------------

def _flat_allreduce(mesh, data, cfg, iters=1):
    W = data.shape[0]
    sh = NamedSharding(mesh, P("dp"))
    g = jax.device_put(data, sh)
    res = jax.device_put(np.zeros_like(data), sh)

    def island(x, r):
        out, nr = comp.all_reduce_flat(
            x[0], "dp", W, cfg,
            residual=(r[0] if cfg is not None and cfg.ef else None))
        return out, (nr[None] if nr is not None else r)

    f = jax.jit(mesh_mod.shard_map_compat(
        island, mesh, (P("dp"), P("dp")), (P(), P("dp"))))
    outs = []
    for _ in range(iters):
        out, res = f(g, res)
        outs.append(np.asarray(out))
    return outs


def test_quantized_allreduce_matches_sum(mesh8):
    rng = np.random.RandomState(0)
    data = rng.randn(8, 2048).astype(np.float32)
    true = data.sum(0)
    for spec in ("int8:block=128", "fp8:block=128"):
        out, = _flat_allreduce(mesh8, data, comp.parse_spec(spec))
        rel = np.abs(out - true).max() / np.abs(true).max()
        assert rel < 0.05, (spec, rel)
    out, = _flat_allreduce(mesh8, data, comp.parse_spec("fp32"))
    np.testing.assert_allclose(out, true, rtol=1e-5, atol=1e-5)


def test_error_feedback_debiases_repeated_reduce(mesh8):
    """EF's defining property: reducing the SAME payload repeatedly,
    the time-average of the quantized outputs converges to the true
    sum (each step re-feeds the previous step's quantization error),
    while the EF-less path repeats the same biased output forever."""
    rng = np.random.RandomState(3)
    data = rng.randn(8, 2048).astype(np.float32)
    true = data.sum(0)
    plain = _flat_allreduce(mesh8, data,
                            comp.parse_spec("int8:block=128"), 8)
    ef = _flat_allreduce(mesh8, data,
                         comp.parse_spec("int8:ef:block=128"), 8)
    err_plain = np.abs(np.mean(plain, 0) - true).max()
    err_ef = np.abs(np.mean(ef, 0) - true).max()
    assert np.array_equal(plain[0], plain[-1])  # no EF: static bias
    assert err_ef < 0.25 * err_plain, (err_ef, err_plain)


# ---------------------------------------------------------------------------
# e2e train gates (8-device mesh)
# ---------------------------------------------------------------------------

def _mse(o, t):
    return ((o - t) ** 2).mean()


def _build_dp8(compress, **kw):
    paddle.seed(0)
    mesh = build_mesh({"dp": 8})
    set_mesh(mesh)
    model = nn.Sequential(nn.Linear(64, 256), nn.ReLU(),
                          nn.Linear(256, 8))
    opt = optim.AdamW(learning_rate=1e-2,
                      parameters=model.parameters())
    step = DistributedTrainStepCompiler(model, opt, loss_fn=_mse,
                                        mesh=mesh,
                                        comm_compress=compress, **kw)
    return model, step


_COMM_KEYS = ("comm/all_reduce/calls", "comm/all_reduce/bytes",
              "comm/all_reduce/wire_bytes")


def _train(compress, steps=10, **kw):
    rng = np.random.RandomState(0)
    xs = [rng.randn(16, 64).astype(np.float32) for _ in range(steps)]
    ys = [rng.randn(16, 8).astype(np.float32) for _ in range(steps)]
    model, step = _build_dp8(compress, **kw)
    read = _delta(_COMM_KEYS)
    losses = [float(step(paddle.to_tensor(x),
                         paddle.to_tensor(y)).item())
              for x, y in zip(xs, ys)]
    comm = read()
    set_mesh(None)
    return losses, comm, step


def test_e2e_int8_ef_wire_ratio_and_loss_parity():
    """THE acceptance gate: int8:ef vs the explicit fp32 twin on the
    8-device mesh — wire_bytes <= 0.3x, loss curve parity, both
    train."""
    l_fp32, c_fp32, _ = _train("fp32")
    l_int8, c_int8, _ = _train("int8:ef:block=256")
    # the twins price the same logical payload...
    assert c_int8["comm/all_reduce/bytes"] == \
        c_fp32["comm/all_reduce/bytes"] > 0
    # ...but the quantized wire carries <= 0.3x the bytes
    ratio = (c_int8["comm/all_reduce/wire_bytes"]
             / c_fp32["comm/all_reduce/wire_bytes"])
    assert ratio <= 0.3, ratio
    # loss-curve parity: every step within 2% of the fp32 twin, and
    # both actually train
    for a, b in zip(l_fp32, l_int8):
        assert abs(a - b) <= 2e-2 * max(1.0, abs(a)), (a, b)
    assert l_fp32[-1] < l_fp32[0] and l_int8[-1] < l_int8[0]


def test_e2e_compress_off_is_bit_identical_and_counter_clean():
    """PADDLE_COMM_COMPRESS unset + no argument: the implicit GSPMD
    program — bit-identical losses to the explicit fp32 twin's math
    path is NOT required (different reduction order); what IS
    required: zero explicit comm counters (no island was built) and
    step-for-step identical losses across two identically-seeded
    uncompressed runs."""
    assert not os.environ.get("PADDLE_COMM_COMPRESS")
    l1, c1, step = _train(None)
    assert step._compress is None and step._comm_state == {}
    assert all(v == 0 for v in c1.values()), c1
    l2, c2, _ = _train(None)
    assert l1 == l2


def test_env_config_drives_fit_compilers(monkeypatch):
    """PADDLE_COMM_COMPRESS wires the quantized allreduce into every
    DistributedTrainStepCompiler built WITHOUT an explicit
    comm_compress argument (the Model.fit path)."""
    monkeypatch.setenv("PADDLE_COMM_COMPRESS", "int8:ef:block=256")
    paddle.seed(0)
    mesh = build_mesh({"dp": 8})
    set_mesh(mesh)
    try:
        model = nn.Sequential(nn.Linear(64, 32), nn.ReLU(),
                              nn.Linear(32, 8))
        opt = optim.SGD(learning_rate=0.1,
                        parameters=model.parameters())
        step = DistributedTrainStepCompiler(model, opt, loss_fn=_mse,
                                            mesh=mesh)
        assert step._compress is not None
        read = _delta(_COMM_KEYS)
        rng = np.random.RandomState(0)
        loss = step(paddle.to_tensor(rng.randn(16, 64)
                                     .astype(np.float32)),
                    paddle.to_tensor(rng.randn(16, 8)
                                     .astype(np.float32)))
        assert np.isfinite(float(loss.item()))
        comm = read()
        assert comm["comm/all_reduce/wire_bytes"] > 0
        assert comm["comm/all_reduce/wire_bytes"] < \
            comm["comm/all_reduce/bytes"]
        # the EF residual is real donated state
        assert "residual" in step._comm_state
    finally:
        set_mesh(None)


def test_env_config_disables_on_hybrid_mesh(monkeypatch):
    """An env-driven config on a model-parallel mesh DISABLES (a pod
    job sets the env once; hybrid members keep GSPMD); an explicit
    constructor spec on the same mesh raises."""
    monkeypatch.setenv("PADDLE_COMM_COMPRESS", "int8")
    paddle.seed(0)
    mesh = build_mesh({"dp": 2, "mp": 4})
    set_mesh(mesh)
    try:
        from paddle_tpu.text.models.gpt import (GPTConfig,
                                                GPTForCausalLM)

        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                        num_heads=2, ffn_hidden=32, max_seq_len=8,
                        remat=False, use_flash_attention=False,
                        dropout=0.0)
        model = GPTForCausalLM(cfg)
        opt = optim.SGD(learning_rate=0.1,
                        parameters=model.parameters())
        step = DistributedTrainStepCompiler(model, opt, mesh=mesh)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 64, (8, 8))
                               .astype(np.int32))
        loss = step(ids, ids)
        assert np.isfinite(float(loss.item()))
        assert step._compress is None  # disabled, not crashed

        m2 = GPTForCausalLM(cfg)
        o2 = optim.SGD(learning_rate=0.1, parameters=m2.parameters())
        s2 = DistributedTrainStepCompiler(m2, o2, mesh=mesh,
                                          comm_compress="int8")
        with pytest.raises(ValueError, match="comm_compress"):
            s2(ids, ids)
    finally:
        set_mesh(None)


def test_fused_dispatch_and_grad_scaler_compose():
    """steps_per_dispatch=2 + GradScaler + guard_nonfinite over the
    compressed step: the residual rides the scan carry, gradients
    unscale before quantizing, and K fused microsteps match 2K
    sequential single dispatches step-for-step (same quantized
    math)."""
    from paddle_tpu import amp

    rng = np.random.RandomState(0)
    xs = [rng.randn(16, 64).astype(np.float32) for _ in range(8)]
    ys = [rng.randn(16, 8).astype(np.float32) for _ in range(8)]

    _, s1 = _build_dp8("int8:ef:block=256",
                       grad_scaler=None)
    seq = [float(s1(paddle.to_tensor(x), paddle.to_tensor(y)).item())
           for x, y in zip(xs, ys)]
    set_mesh(None)

    _, s2 = _build_dp8("int8:ef:block=256", steps_per_dispatch=2,
                       grad_scaler=None)
    fused = []
    for i in range(0, 8, 2):
        out = s2(paddle.to_tensor(np.stack(xs[i:i + 2])),
                 paddle.to_tensor(np.stack(ys[i:i + 2])))
        fused.extend(float(v) for v in np.asarray(out.numpy()))
    set_mesh(None)
    np.testing.assert_array_equal(seq, fused)

    _, s3 = _build_dp8("int8:ef:block=256", guard_nonfinite=True,
                       grad_scaler=amp.GradScaler(
                           init_loss_scaling=2.0 ** 10))
    scaled = [float(s3(paddle.to_tensor(x),
                       paddle.to_tensor(y)).item())
              for x, y in zip(xs[:4], ys[:4])]
    set_mesh(None)
    assert np.isfinite(scaled).all() and s3.last_skips == 0
    # unscale-before-quantize: the scaled run's losses match the
    # unscaled run's (quantization sees the same gradient values)
    for a, b in zip(seq[:4], scaled):
        assert abs(a - b) <= 1e-5 * max(1.0, abs(a)), (a, b)


# ---------------------------------------------------------------------------
# elastic checkpoint round-trip
# ---------------------------------------------------------------------------

def test_ef_residual_checkpoint_roundtrip_bit_identical():
    """Acceptance: the EF residual round-trips through training-state
    snapshot/restore with bit-identical resumed training — and
    WITHOUT the residual the resumed run diverges (the buffer is
    load-bearing state, not decoration)."""
    rng = np.random.RandomState(0)
    xs = [rng.randn(16, 64).astype(np.float32) for _ in range(10)]
    ys = [rng.randn(16, 8).astype(np.float32) for _ in range(10)]

    m1, s1 = _build_dp8("int8:ef:block=256")
    for i in range(5):
        s1(paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i]))
    # the snapshot a CheckpointManager would host-copy (hapi
    # _training_state reads exactly these fields)
    slots = {k: {s: np.asarray(v) for s, v in sl.items()}
             for k, sl in s1._opt_state.items()}
    residuals = {k: np.asarray(v) for k, v in s1._comm_state.items()}
    assert "residual" in residuals
    assert np.abs(residuals["residual"]).max() > 0  # EF really ran
    sd = {k: np.asarray(v._value if hasattr(v, "_value") else v)
          for k, v in m1.state_dict().items()}
    cont = [float(s1(paddle.to_tensor(xs[i]),
                     paddle.to_tensor(ys[i])).item())
            for i in range(5, 10)]
    set_mesh(None)

    m2, s2 = _build_dp8("int8:ef:block=256")
    m2.set_state_dict(sd)
    s2.restore_state(slots, step=5, comm=residuals)
    resumed = [float(s2(paddle.to_tensor(xs[i]),
                        paddle.to_tensor(ys[i])).item())
               for i in range(5, 10)]
    set_mesh(None)
    assert cont == resumed  # bit-identical

    m3, s3 = _build_dp8("int8:ef:block=256")
    m3.set_state_dict(sd)
    s3.restore_state(slots, step=5)  # residual dropped
    stale = [float(s3(paddle.to_tensor(xs[i]),
                      paddle.to_tensor(ys[i])).item())
             for i in range(5, 10)]
    set_mesh(None)
    assert cont != stale


def test_training_state_snapshot_carries_opt_comm():
    """hapi Model._training_state embeds the residual under
    'opt_comm' and _restore_training_state routes it back into the
    next compiler's preload."""
    from paddle_tpu.hapi import Model
    from paddle_tpu.nn import Linear

    paddle.seed(0)
    mesh = build_mesh({"dp": 8})
    set_mesh(mesh)
    try:
        net = nn.Sequential(Linear(64, 32), nn.ReLU(), Linear(32, 8))
        model = Model(net)
        opt = optim.SGD(learning_rate=0.1, parameters=net.parameters())
        model.prepare(opt, _mse)
        comp_step = DistributedTrainStepCompiler(
            net, opt, loss_fn=_mse, mesh=mesh,
            comm_compress="int8:ef:block=256")
        rng = np.random.RandomState(0)
        comp_step(paddle.to_tensor(rng.randn(16, 64)
                                   .astype(np.float32)),
                  paddle.to_tensor(rng.randn(16, 8)
                                   .astype(np.float32)))
        model._compiled_step = comp_step
        state = model._training_state()
        assert state["opt_comm"] is not None
        assert "residual" in state["opt_comm"]
    finally:
        set_mesh(None)


# ---------------------------------------------------------------------------
# chaos site
# ---------------------------------------------------------------------------

def test_chaos_comm_compress_raise_and_disarmed_clean():
    with chaos.inject("comm_compress", "raise") as rule:
        with pytest.raises(chaos.ChaosInjected):
            _train("int8:block=256", steps=1)
        assert rule.triggers == 1
    set_mesh(None)
    assert cmon.stat_get("chaos/comm_compress/raise/triggered") == 1
    # disarmed rebuild: clean, and no further chaos counters move
    t0 = cmon.stat_get("chaos/comm_compress/raise/triggered")
    losses, _, _ = _train("int8:block=256", steps=2)
    assert np.isfinite(losses).all()
    assert cmon.stat_get("chaos/comm_compress/raise/triggered") == t0


def test_chaos_bitflip_corrupts_one_block_deterministically():
    """The bitflip fault bakes a one-block wire corruption into the
    built program: losses visibly diverge from the clean run but
    stay finite, and the trigger counter proves exactly one
    injection (one build)."""
    clean, _, _ = _train("int8:block=256", steps=4)
    with chaos.inject("comm_compress", "bitflip") as rule:
        hurt, _, _ = _train("int8:block=256", steps=4)
        assert rule.triggers == 1  # once per build, not per step
    set_mesh(None)
    assert np.isfinite(hurt).all()
    assert clean != hurt
    assert cmon.stat_get(
        "chaos/comm_compress/bitflip/triggered") >= 1


def test_chaos_bitflip_rejected_outside_comm_compress():
    with pytest.raises(ValueError):
        chaos.parse_spec("dispatch:bitflip")


# ---------------------------------------------------------------------------
# PTA08x sanitizers
# ---------------------------------------------------------------------------

def test_pta080_undonated_residual_raises_under_sanitize():
    msan.configure("compress")
    try:
        with pytest.raises(ValueError, match="PTA080"):
            _train("int8:ef:block=256", steps=1, donate=False)
    finally:
        msan.disarm()
        set_mesh(None)
    assert cmon.stat_get("analysis/PTA080/findings") >= 1
    # disarmed: the same build proceeds (wasteful but workable)
    losses, _, _ = _train("int8:ef:block=256", steps=1, donate=False)
    assert np.isfinite(losses).all()


def test_pta081_nonsum_compress_falls_back(mesh8):
    g = mesh_mod.new_group_for_axes(("dp",))
    data = np.random.RandomState(0).randn(8, 256).astype(np.float32)

    def island(x):
        t = Tensor(x[0], stop_gradient=True, _internal=True)
        C.all_reduce(t, op=C.ReduceOp.MAX, group=g, compress="int8")
        return t._value

    f = jax.jit(mesh_mod.shard_map_compat(island, mesh8,
                                          (P("dp"),), P()))
    out = f(jax.device_put(data, NamedSharding(mesh8, P("dp"))))
    np.testing.assert_allclose(np.asarray(out), data.max(0),
                               rtol=1e-6)  # silent fp32 fallback
    msan.configure("compress")
    try:
        f2 = jax.jit(mesh_mod.shard_map_compat(island, mesh8,
                                               (P("dp"),), P()))
        with pytest.raises(ValueError, match="PTA081"):
            f2(jax.device_put(data + 1,
                              NamedSharding(mesh8, P("dp"))))
    finally:
        msan.disarm()
    assert cmon.stat_get("analysis/PTA081/findings") >= 1


def test_pta081_integer_dtype_falls_back(mesh8):
    g = mesh_mod.new_group_for_axes(("dp",))
    data = np.arange(8 * 256, dtype=np.int32).reshape(8, 256)

    def island(x):
        t = Tensor(x[0], stop_gradient=True, _internal=True)
        C.all_reduce(t, group=g, compress="int8")
        return t._value

    f = jax.jit(mesh_mod.shard_map_compat(island, mesh8,
                                          (P("dp"),), P()))
    out = f(jax.device_put(data, NamedSharding(mesh8, P("dp"))))
    np.testing.assert_array_equal(np.asarray(out), data.sum(0))


def test_compress_static_lints():
    from paddle_tpu.analysis.compress import lint_compress_source

    src = """
def bad(grads, res, C, ReduceOp):
    reduce_tree(grads, SEGS, 'dp', 8, CFG, residual=res)
    out = all_reduce_flat(flat, 'dp', 8, CFG, residual=res)
    C.all_reduce(t, op=ReduceOp.MAX, compress="int8")

def also_bad(grads, res):
    g, new_res = reduce_tree(grads, SEGS, 'dp', 8, CFG, residual=res)
    return g

def self_update_dropped(grads, res):
    out, res = reduce_tree(grads, SEGS, 'dp', 8, CFG, residual=res)
    return out

def fine(grads, res, C):
    g, new_res = reduce_tree(grads, SEGS, 'dp', 8, CFG, residual=res)
    C.all_reduce(t, op=ReduceOp.SUM, compress="int8")
    return g, new_res

def fine_ef_loop(grads, res, data):
    for _ in data:
        grads, res = reduce_tree(grads, SEGS, 'dp', 8, CFG,
                                 residual=res)
    return grads
"""
    rep = lint_compress_source(src, filename="x.py")
    codes = sorted(f.code for f in rep.findings)
    assert codes.count("PTA081") == 1
    # discarded call + bound-but-dead result + dead tuple slot +
    # the straight-line self-update whose RHS read is the OLD
    # binding (the canonical EF LOOP, where that read consumes the
    # previous iteration's new residual, stays clean)
    assert codes.count("PTA080") == 4, [f.format() for f in
                                        rep.findings]
    # the clean function contributes nothing
    fine_line = src[:src.index("def fine")].count("\n") + 1
    assert all(f.line < fine_line for f in rep.findings)


def test_sanitize_family_registered():
    assert "compress" in msan.FAMILIES
    fams = msan.parse_spec("compress")
    assert "compress" in fams
    from paddle_tpu.analysis.cli import SANITIZE_FAMILIES

    assert "compress" in SANITIZE_FAMILIES


def test_disarmed_run_leaves_zero_sanitize_counters():
    """The bench provenance contract: a compressed run with nothing
    armed must not move sanitize/PTA08x counters."""
    before = (cmon.stat_get("analysis/PTA080/findings"),
              cmon.stat_get("analysis/PTA081/findings"),
              cmon.stat_get("sanitize/findings"))
    losses, _, _ = _train("int8:ef:block=256", steps=2)
    assert np.isfinite(losses).all()
    after = (cmon.stat_get("analysis/PTA080/findings"),
             cmon.stat_get("analysis/PTA081/findings"),
             cmon.stat_get("sanitize/findings"))
    assert before == after


# ---------------------------------------------------------------------------
# collective payload accounting (the ISSUE-14 fix)
# ---------------------------------------------------------------------------

def test_all_gather_counts_full_payload(mesh8):
    """Regression (ISSUE-14 satellite): comm/all_gather/bytes (and
    the flight event) price the FULL gathered payload — group_size x
    the per-rank tensor — not the first tensor's bytes."""
    g = mesh_mod.new_group_for_axes(("dp",))
    data = np.random.RandomState(0).randn(8, 512).astype(np.float32)
    read = _delta(("comm/all_gather/bytes",
                   "comm/all_gather/wire_bytes"))

    def island(x):
        parts = []
        C.all_gather(parts, Tensor(x[0], stop_gradient=True,
                                   _internal=True), group=g)
        return jnp.stack([p._value for p in parts], axis=0)

    f = jax.jit(mesh_mod.shard_map_compat(island, mesh8,
                                          (P("dp"),), P()))
    out = f(jax.device_put(data, NamedSharding(mesh8, P("dp"))))
    np.testing.assert_allclose(np.asarray(out), data, rtol=1e-6)
    d = read()
    assert d["comm/all_gather/bytes"] == 8 * 512 * 4
    assert d["comm/all_gather/wire_bytes"] == 8 * 512 * 4


def test_plain_collectives_wire_equals_bytes(mesh8):
    g = mesh_mod.new_group_for_axes(("dp",))
    data = np.random.RandomState(0).randn(8, 128).astype(np.float32)
    read = _delta(("comm/all_reduce/bytes",
                   "comm/all_reduce/wire_bytes"))

    def island(x):
        t = Tensor(x[0], stop_gradient=True, _internal=True)
        C.all_reduce(t, group=g)
        return t._value

    f = jax.jit(mesh_mod.shard_map_compat(island, mesh8,
                                          (P("dp"),), P()))
    f(jax.device_put(data, NamedSharding(mesh8, P("dp"))))
    d = read()
    assert d["comm/all_reduce/bytes"] == 128 * 4
    assert d["comm/all_reduce/wire_bytes"] == 128 * 4


def test_scatter_counts_list_payload():
    t = paddle.to_tensor(np.zeros((4, 4), np.float32))
    parts = [paddle.to_tensor(np.full((4, 4), i, np.float32))
             for i in range(2)]
    read = _delta(("comm/scatter/bytes",))
    C.scatter(t, parts, src=0)
    assert read()["comm/scatter/bytes"] == 2 * 4 * 4 * 4


# ---------------------------------------------------------------------------
# doc drift
# ---------------------------------------------------------------------------

class TestDocDrift:
    def _readme(self):
        with open(os.path.join(REPO, "README.md")) as f:
            return f.read()

    def test_readme_covers_quantized_comm(self):
        doc = self._readme()
        assert "Quantized communication" in doc
        for needle in ("PADDLE_COMM_COMPRESS", "PADDLE_COMM_BLOCK",
                       "int8", "error feedback", "wire_bytes",
                       "comm_compress"):
            assert needle in doc, f"{needle!r} missing from README"

    def test_readme_covers_pta08x_and_chaos_site(self):
        doc = self._readme()
        for code in ("PTA080", "PTA081"):
            assert code in doc, f"{code} missing from README"
        assert "comm_compress" in doc and "bitflip" in doc
