"""Subprocess worker for the elastic-resume SIGKILL harness
(tests/test_elastic.py): a small deterministic regression fit with
per-step async training-state checkpoints. Each completed step appends
"<global_step> <loss.hex()>" to $ELASTIC_LOSS_LOG (fsync'd, so lines
survive a SIGKILL mid-run). Relaunching with the same
PADDLE_JOB_ID/PADDLE_CKPT_DIR resumes from the newest durable snapshot
and must reproduce the uninterrupted run's losses bit-for-bit.
"""
import os
import sys

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim
import paddle_tpu.optimizer.lr as lr
from paddle_tpu.hapi import Model
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.io import BatchSampler, DataLoader, TensorDataset

LOG = os.environ["ELASTIC_LOSS_LOG"]
EPOCHS = int(os.environ.get("ELASTIC_EPOCHS", "4"))
STALL_AT = int(os.environ.get("ELASTIC_STALL_AT", "-1"))


class LossLog(Callback):
    """Appends the just-completed step's (0-based) global step + loss.
    Runs BEFORE the training-state saver (fit appends its saver last),
    so mgr.global_step is still the pre-increment completed count."""

    def on_train_batch_end(self, step, logs=None):
        g = self.model._ckpt_manager.global_step
        with open(LOG, "a") as f:
            f.write(f"{g} {float(logs['loss']).hex()}\n")
            f.flush()
            os.fsync(f.fileno())
        if g == STALL_AT:
            # parked forever: gives the parent a deterministic window
            # to SIGKILL after step STALL_AT's checkpoint enqueued
            self.model._ckpt_manager.flush()
            import time

            while True:
                time.sleep(0.5)


def main():
    paddle.seed(0)
    rng = np.random.RandomState(7)
    x = rng.randn(48, 10).astype(np.float32)
    w = rng.randn(10, 1).astype(np.float32)
    y = (x @ w + 0.1 * rng.randn(48, 1)).astype(np.float32)
    ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(y)])
    sampler = BatchSampler(ds, shuffle=True, batch_size=8,
                           drop_last=True, seed=11)
    loader = DataLoader(ds, batch_sampler=sampler)
    net = nn.Linear(10, 1)
    model = Model(net)
    sched = lr.StepDecay(learning_rate=0.05, step_size=5, gamma=0.5)
    opt = optim.Adam(learning_rate=sched,
                     parameters=net.parameters())
    model.prepare(opt, lambda o, t: ((o - t) ** 2).mean())
    model.fit(loader, epochs=EPOCHS, verbose=0, resume="auto",
              callbacks=[LossLog()])
    return 0


if __name__ == "__main__":
    sys.exit(main())
