"""Round-3 op families: detection (roi_align/roi_pool/psroi_pool/
yolo_box/prior_box/box_coder/iou_similarity/deform_conv2d/affine_grid),
sequence-LoD ops, ctc_loss, edit_distance, beam search.

Each op is validated against an independent numpy reference
(the reference repo's OpTest pattern: unittests/op_test.py:282) and
grad-checked where the reference op is differentiable."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.lod import LoDTensor
from paddle_tpu.vision import ops as vops


def T(x):
    return paddle.to_tensor(np.asarray(x))


# ---------------------------------------------------------------------------
# detection
# ---------------------------------------------------------------------------

def _np_roi_align(x, boxes, box_batch, ph, pw, scale, ratio, aligned):
    n, c, h, w = x.shape
    out = np.zeros((len(boxes), c, ph, pw), np.float32)
    off = 0.5 if aligned else 0.0
    for r, (bb, b) in enumerate(zip(boxes, box_batch)):
        x1, y1, x2, y2 = bb * scale - off
        rw, rh = x2 - x1, y2 - y1
        if not aligned:
            rw, rh = max(rw, 1.0), max(rh, 1.0)
        bw, bh = rw / pw, rh / ph
        for i in range(ph):
            for j in range(pw):
                acc = np.zeros(c, np.float32)
                for iy in range(ratio):
                    for ix in range(ratio):
                        yy = y1 + (i + (iy + 0.5) / ratio) * bh
                        xx = x1 + (j + (ix + 0.5) / ratio) * bw
                        if yy < -1 or yy > h or xx < -1 or xx > w:
                            continue
                        yy_c = min(max(yy, 0.0), h - 1.0)
                        xx_c = min(max(xx, 0.0), w - 1.0)
                        y0, x0 = int(np.floor(yy_c)), int(np.floor(xx_c))
                        y1i, x1i = min(y0 + 1, h - 1), min(x0 + 1, w - 1)
                        ly = yy_c - y0
                        lx = xx_c - x0
                        acc += ((1 - ly) * (1 - lx) * x[b, :, y0, x0]
                                + (1 - ly) * lx * x[b, :, y0, x1i]
                                + ly * (1 - lx) * x[b, :, y1i, x0]
                                + ly * lx * x[b, :, y1i, x1i])
                out[r, :, i, j] = acc / (ratio * ratio)
    return out


def test_roi_align_matches_numpy_and_grad():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    boxes = np.asarray([[0.5, 0.5, 6.0, 6.0], [1.0, 2.0, 7.5, 7.0],
                        [0.0, 0.0, 4.0, 4.0]], np.float32)
    boxes_num = np.asarray([2, 1], np.int32)
    out = vops.roi_align(T(x), T(boxes), T(boxes_num), 4,
                         spatial_scale=0.5, sampling_ratio=2)
    ref = _np_roi_align(x, boxes, [0, 0, 1], 4, 4, 0.5, 2, True)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
    # differentiable wrt x
    xt = T(x)
    xt.stop_gradient = False
    loss = vops.roi_align(xt, T(boxes), T(boxes_num), 4,
                          spatial_scale=0.5, sampling_ratio=2).sum()
    loss.backward()
    assert np.isfinite(xt.grad.numpy()).all()
    assert np.abs(xt.grad.numpy()).sum() > 0


def test_roi_align_adaptive_ratio_raises():
    with pytest.raises(NotImplementedError, match="sampling_ratio"):
        vops.roi_align(T(np.zeros((1, 1, 4, 4), np.float32)),
                       T(np.zeros((1, 4), np.float32)),
                       T(np.asarray([1], np.int32)), 2)


def test_roi_pool_matches_numpy():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 8, 8).astype(np.float32)
    boxes = np.asarray([[0.0, 0.0, 7.0, 7.0], [2.0, 2.0, 6.0, 6.0]],
                       np.float32)
    out = vops.roi_pool(T(x), T(boxes), T(np.asarray([2], np.int32)), 2,
                        spatial_scale=1.0)
    # numpy reference (reference roi_pool_op.h integer-bin max)
    ref = np.zeros((2, 2, 2, 2), np.float32)
    for r, bb in enumerate(boxes):
        x1, y1, x2, y2 = np.round(bb).astype(int)
        rh, rw = max(y2 - y1 + 1, 1), max(x2 - x1 + 1, 1)
        for i in range(2):
            for j in range(2):
                hs = int(np.floor(i * rh / 2)) + y1
                he = int(np.ceil((i + 1) * rh / 2)) + y1
                ws = int(np.floor(j * rw / 2)) + x1
                we = int(np.ceil((j + 1) * rw / 2)) + x1
                hs, he = max(hs, 0), min(he, 8)
                ws, we = max(ws, 0), min(we, 8)
                if he <= hs or we <= ws:
                    continue
                ref[r, :, i, j] = x[0, :, hs:he, ws:we].max((1, 2))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)


def test_psroi_pool_matches_numpy():
    rng = np.random.RandomState(2)
    ph = pw = 2
    out_c = 3
    x = rng.randn(1, out_c * ph * pw, 6, 6).astype(np.float32)
    boxes = np.asarray([[0.0, 0.0, 5.0, 5.0]], np.float32)
    out = vops.psroi_pool(T(x), T(boxes), T(np.asarray([1], np.int32)),
                          2, spatial_scale=1.0)
    assert out.shape == [1, out_c, ph, pw]
    # reference: avg over bin of channel (c*ph + i)*pw + j
    x1, y1 = 0.0, 0.0
    x2, y2 = 6.0, 6.0  # round(5)+1
    bh, bw = (y2 - y1) / ph, (x2 - x1) / pw
    ref = np.zeros((1, out_c, ph, pw), np.float32)
    for c in range(out_c):
        for i in range(ph):
            for j in range(pw):
                hs = int(np.clip(np.floor(i * bh + y1), 0, 6))
                he = int(np.clip(np.ceil((i + 1) * bh + y1), 0, 6))
                ws = int(np.clip(np.floor(j * bw + x1), 0, 6))
                we = int(np.clip(np.ceil((j + 1) * bw + x1), 0, 6))
                ch = (c * ph + i) * pw + j
                if he > hs and we > ws:
                    ref[0, c, i, j] = x[0, ch, hs:he, ws:we].mean()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_yolo_box_matches_numpy():
    rng = np.random.RandomState(3)
    an = [10, 13, 16, 30]  # 2 anchors
    class_num = 2
    n, h, w = 1, 3, 3
    x = rng.randn(n, 2 * (5 + class_num), h, w).astype(np.float32)
    img = np.asarray([[96, 96]], np.int32)
    boxes, scores = vops.yolo_box(T(x), T(img), an, class_num,
                                  conf_thresh=0.0, downsample_ratio=32)
    sig = lambda v: 1 / (1 + np.exp(-v))
    px = x.reshape(n, 2, 5 + class_num, h, w)
    ref_b = np.zeros((n, 2 * h * w, 4), np.float32)
    ref_s = np.zeros((n, 2 * h * w, class_num), np.float32)
    for a in range(2):
        for k in range(h):
            for l in range(w):
                cx = (l + sig(px[0, a, 0, k, l])) * 96 / w
                cy = (k + sig(px[0, a, 1, k, l])) * 96 / h
                bw = np.exp(px[0, a, 2, k, l]) * an[2 * a] * 96 / (32 * w)
                bh = np.exp(px[0, a, 3, k, l]) * an[2 * a + 1] * 96 / (32 * h)
                conf = sig(px[0, a, 4, k, l])
                idx = a * h * w + k * w + l
                bb = [cx - bw / 2, cy - bh / 2, cx + bw / 2, cy + bh / 2]
                bb[0] = max(bb[0], 0)
                bb[1] = max(bb[1], 0)
                bb[2] = min(bb[2], 95)
                bb[3] = min(bb[3], 95)
                ref_b[0, idx] = bb
                ref_s[0, idx] = conf * sig(px[0, a, 5:, k, l])
    np.testing.assert_allclose(boxes.numpy(), ref_b, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(scores.numpy(), ref_s, rtol=1e-4, atol=1e-5)


def test_prior_box_basic():
    feat = T(np.zeros((1, 8, 4, 4), np.float32))
    img = T(np.zeros((1, 3, 32, 32), np.float32))
    boxes, var = vops.prior_box(feat, img, min_sizes=[8.0],
                                aspect_ratios=[1.0, 2.0], clip=True)
    # expanded aspect ratios = [1.0, 2.0] and no max_sizes -> 2 priors
    assert boxes.shape == [4, 4, 2, 4]
    b = boxes.numpy()
    assert np.all(b >= 0.0) and np.all(b <= 1.0)
    v = var.numpy()
    np.testing.assert_allclose(v[..., 0], 0.1, rtol=1e-6)
    # center of cell (0,0): (0.5*8, 0.5*8) = (4, 4); min_size 8 ar=1 →
    # box (0, 0, 8, 8)/32
    np.testing.assert_allclose(b[0, 0, 0], [0, 0, 0.25, 0.25], atol=1e-6)


def test_box_coder_encode_decode_roundtrip():
    rng = np.random.RandomState(4)
    priors = np.abs(rng.randn(5, 4).astype(np.float32)) + \
        np.asarray([0, 0, 2, 2], np.float32)
    targets = np.abs(rng.randn(3, 4).astype(np.float32)) + \
        np.asarray([0, 0, 2, 2], np.float32)
    var = [0.1, 0.1, 0.2, 0.2]
    enc = vops.box_coder(T(priors), var, T(targets),
                         code_type="encode_center_size")
    assert enc.shape == [3, 5, 4]
    dec = vops.box_coder(T(priors), var, enc,
                         code_type="decode_center_size", axis=0)
    # decoding the encoding of target i against prior j recovers target i
    for j in range(5):
        np.testing.assert_allclose(dec.numpy()[:, j], targets, rtol=1e-4,
                                   atol=1e-4)


def test_iou_similarity():
    a = np.asarray([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32)
    b = np.asarray([[0, 0, 2, 2], [2, 2, 4, 4]], np.float32)
    iou = vops.iou_similarity(T(a), T(b)).numpy()
    np.testing.assert_allclose(iou[0, 0], 1.0)
    np.testing.assert_allclose(iou[1, 1], 1.0 / 7.0, rtol=1e-5)
    np.testing.assert_allclose(iou[0, 1], 0.0)


def test_deform_conv2d_zero_offset_equals_conv():
    """With zero offsets and mask=None, deform_conv2d == conv2d."""
    rng = np.random.RandomState(5)
    x = rng.randn(2, 4, 6, 6).astype(np.float32)
    w = rng.randn(8, 4, 3, 3).astype(np.float32)
    off = np.zeros((2, 2 * 9, 6, 6), np.float32)
    out = vops.deform_conv2d(T(x), T(off), T(w), padding=1)
    ref = F.conv2d(T(x), T(w), padding=1)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)
    # v2: mask of ones is also identity
    mask = np.ones((2, 9, 6, 6), np.float32)
    out2 = vops.deform_conv2d(T(x), T(off), T(w), padding=1, mask=T(mask))
    np.testing.assert_allclose(out2.numpy(), ref.numpy(), rtol=1e-4,
                               atol=1e-4)


def test_deform_conv2d_grad_flows():
    rng = np.random.RandomState(6)
    x = T(rng.randn(1, 2, 5, 5).astype(np.float32))
    w = T(rng.randn(3, 2, 3, 3).astype(np.float32))
    off = T(0.1 * rng.randn(1, 18, 5, 5).astype(np.float32))
    x.stop_gradient = False
    w.stop_gradient = False
    off.stop_gradient = False
    loss = vops.deform_conv2d(x, off, w, padding=1).square().sum()
    loss.backward()
    for t in (x, w, off):
        assert np.abs(t.grad.numpy()).sum() > 0


def test_affine_grid_identity():
    theta = np.tile(np.asarray([[[1.0, 0, 0], [0, 1.0, 0]]], np.float32),
                    (2, 1, 1))
    grid = F.affine_grid(T(theta), [2, 3, 4, 4]).numpy()
    assert grid.shape == (2, 4, 4, 2)
    np.testing.assert_allclose(grid[0, 0, 0], [-1, -1], atol=1e-6)
    np.testing.assert_allclose(grid[0, -1, -1], [1, 1], atol=1e-6)
    # translation-only theta shifts the grid
    theta2 = np.asarray([[[1.0, 0, 0.5], [0, 1.0, -0.25]]], np.float32)
    g2 = F.affine_grid(T(theta2), [1, 1, 4, 4]).numpy()
    np.testing.assert_allclose(g2[0, 0, 0], [-0.5, -1.25], atol=1e-6)


def test_nms_and_fpn_distribute():
    boxes = np.asarray([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                       np.float32)
    scores = np.asarray([0.9, 0.8, 0.7], np.float32)
    keep = vops.nms(T(boxes), 0.5, T(scores)).numpy()
    assert list(keep) == [0, 2]
    rois = np.asarray([[0, 0, 10, 10], [0, 0, 100, 100]], np.float32)
    outs, restore, nums = vops.distribute_fpn_proposals(
        T(rois), 2, 5, 4, 224)
    assert sum(int(n.numpy()[0]) for n in nums) == 2
    # per-image rois_num: counts preserved per level AND per image
    rois2 = np.asarray([[0, 0, 10, 10], [0, 0, 100, 100],
                        [0, 0, 12, 12]], np.float32)
    outs2, restore2, nums2 = vops.distribute_fpn_proposals(
        T(rois2), 2, 5, 4, 224, rois_num=T(np.asarray([2, 1], np.int64)))
    for n in nums2:
        assert n.shape == [2]  # one count per image
    total = np.stack([n.numpy() for n in nums2]).sum(0)
    np.testing.assert_array_equal(total, [2, 1])


# ---------------------------------------------------------------------------
# sequence ops
# ---------------------------------------------------------------------------

def _lod_x():
    rng = np.random.RandomState(7)
    v = rng.randn(6, 3).astype(np.float32)
    return v, LoDTensor(paddle.to_tensor(v), lod=[[0, 2, 5, 6]])


def test_sequence_pool_all_types():
    v, x = _lod_x()
    segs = [v[0:2], v[2:5], v[5:6]]
    for ptype, ref_fn in [
            ("sum", lambda s: s.sum(0)),
            ("average", lambda s: s.mean(0)),
            ("sqrt", lambda s: s.sum(0) / np.sqrt(len(s))),
            ("max", lambda s: s.max(0)),
            ("first", lambda s: s[0]),
            ("last", lambda s: s[-1])]:
        out = paddle.static.nn.sequence_pool(x, ptype).numpy()
        ref = np.stack([ref_fn(s) for s in segs])
        np.testing.assert_allclose(out, ref, rtol=1e-5,
                                   err_msg=f"pool_type={ptype}")


def test_sequence_softmax():
    rng = np.random.RandomState(8)
    v = rng.randn(6).astype(np.float32)
    x = LoDTensor(paddle.to_tensor(v), lod=[[0, 2, 6]])
    out = paddle.static.nn.sequence_softmax(x)
    o = out._tensor.numpy()
    np.testing.assert_allclose(o[0:2].sum(), 1.0, rtol=1e-5)
    np.testing.assert_allclose(o[2:6].sum(), 1.0, rtol=1e-5)
    ref = np.exp(v[0:2] - v[0:2].max())
    np.testing.assert_allclose(o[0:2], ref / ref.sum(), rtol=1e-5)


def test_sequence_expand_and_expand_as():
    v, x = _lod_x()
    y = LoDTensor(paddle.to_tensor(np.zeros((5, 1), np.float32)),
                  lod=[[0, 2, 3, 5]])  # repeat counts 2, 1, 2
    out = paddle.static.nn.sequence_expand(x, y)
    o = out._tensor.numpy()
    ref = np.concatenate([v[0:2], v[0:2], v[2:5], v[5:6], v[5:6]])
    np.testing.assert_allclose(o, ref)
    # expand_as: 3 rows -> lengths of y2's sequences
    x2 = paddle.to_tensor(np.arange(3, dtype=np.float32)[:, None])
    y2 = LoDTensor(paddle.to_tensor(np.zeros((6, 1), np.float32)),
                   lod=[[0, 1, 3, 6]])
    o2 = paddle.static.nn.sequence_expand_as(x2, y2)._tensor.numpy()
    np.testing.assert_allclose(o2[:, 0], [0, 1, 1, 2, 2, 2])


def test_sequence_conv_matches_numpy():
    v, x = _lod_x()
    rng = np.random.RandomState(9)
    w = rng.randn(9, 4).astype(np.float32)  # filter_size 3, D=3 -> 9
    out = paddle.static.nn.sequence_conv(x, paddle.to_tensor(w), 3)
    o = out._tensor.numpy()
    offs = [0, 2, 5, 6]
    ref = np.zeros((6, 4), np.float32)
    for a, b in zip(offs, offs[1:]):
        for t in range(a, b):
            ctx = np.zeros((3, 3), np.float32)
            for k in range(3):
                src = t - 1 + k
                if a <= src < b:
                    ctx[k] = v[src]
            ref[t] = ctx.reshape(-1) @ w
    np.testing.assert_allclose(o, ref, rtol=1e-4, atol=1e-5)


def test_sequence_reverse_pad_unpad_slice():
    v, x = _lod_x()
    o = paddle.static.nn.sequence_reverse(x)._tensor.numpy()
    ref = np.concatenate([v[0:2][::-1], v[2:5][::-1], v[5:6]])
    np.testing.assert_allclose(o, ref)
    padded, lens = paddle.static.nn.sequence_pad(x, 0.0)
    assert padded.shape == [3, 3, 3]
    np.testing.assert_allclose(lens.numpy(), [2, 3, 1])
    np.testing.assert_allclose(padded.numpy()[0, :2], v[0:2])
    assert (padded.numpy()[0, 2] == 0).all()
    back = paddle.static.nn.sequence_unpad(padded, lens)
    np.testing.assert_allclose(back._tensor.numpy(), v)
    assert back.lod() == [[0, 2, 5, 6]]
    sl = paddle.static.nn.sequence_slice(
        x, np.asarray([0, 1, 0]), np.asarray([1, 2, 1]))
    np.testing.assert_allclose(sl._tensor.numpy(),
                               np.concatenate([v[0:1], v[3:5], v[5:6]]))


def test_sequence_enumerate():
    ids = LoDTensor(paddle.to_tensor(np.asarray([1, 2, 3, 4, 5],
                                                np.int64)),
                    lod=[[0, 3, 5]])
    out = paddle.static.nn.sequence_enumerate(ids, 2, pad_value=0)
    np.testing.assert_array_equal(
        out._tensor.numpy(),
        [[1, 2], [2, 3], [3, 0], [4, 5], [5, 0]])


# ---------------------------------------------------------------------------
# ctc / edit distance / beam search
# ---------------------------------------------------------------------------

def _np_ctc_loss(logits, labels, in_lens, lab_lens, blank):
    """Direct log-semiring reference (per-sample python DP)."""
    T_, B, C = logits.shape
    lp = logits - logits.max(-1, keepdims=True)
    lp = lp - np.log(np.exp(lp).sum(-1, keepdims=True))
    losses = []
    for b in range(B):
        L = int(lab_lens[b])
        Tb = int(in_lens[b])
        ext = [blank]
        for t in labels[b, :L]:
            ext += [int(t), blank]
        S = len(ext)
        alpha = np.full((Tb, S), -np.inf)
        alpha[0, 0] = lp[0, b, ext[0]]
        if S > 1:
            alpha[0, 1] = lp[0, b, ext[1]]
        for t in range(1, Tb):
            for s in range(S):
                cands = [alpha[t - 1, s]]
                if s >= 1:
                    cands.append(alpha[t - 1, s - 1])
                if s >= 2 and ext[s] != blank and ext[s] != ext[s - 2]:
                    cands.append(alpha[t - 1, s - 2])
                m = max(cands)
                alpha[t, s] = (m + np.log(sum(np.exp(c - m)
                                              for c in cands))
                               if m > -np.inf else -np.inf) + \
                    lp[t, b, ext[s]]
        ends = [alpha[Tb - 1, S - 1]]
        if S > 1:
            ends.append(alpha[Tb - 1, S - 2])
        m = max(ends)
        losses.append(-(m + np.log(sum(np.exp(e - m) for e in ends))))
    return np.asarray(losses, np.float32)


def test_ctc_loss_matches_numpy_and_grad():
    rng = np.random.RandomState(10)
    T_, B, C = 6, 2, 5
    logits = rng.randn(T_, B, C).astype(np.float32)
    labels = np.asarray([[1, 2, 3], [2, 2, 0]], np.int32)
    in_lens = np.asarray([6, 4], np.int64)
    lab_lens = np.asarray([3, 2], np.int64)
    ref = _np_ctc_loss(logits, labels, in_lens, lab_lens, 0)
    out = F.ctc_loss(T(logits), T(labels), T(in_lens), T(lab_lens),
                     blank=0, reduction="none")
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)
    # mean reduction = mean(loss / label_lengths) (paddle parity,
    # nn/functional/loss.py ctc_loss)
    m = F.ctc_loss(T(logits), T(labels), T(in_lens), T(lab_lens),
                   reduction="mean")
    np.testing.assert_allclose(float(m.item()),
                               np.mean(ref / lab_lens), rtol=1e-4)
    lt = T(logits)
    lt.stop_gradient = False
    loss = F.ctc_loss(lt, T(labels), T(in_lens), T(lab_lens))
    loss.backward()
    g = lt.grad.numpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0
    # rows past a sample's input length carry no gradient
    assert np.abs(g[4:, 1]).sum() < 1e-6


def test_ctc_loss_layer():
    import paddle_tpu.nn as nn

    rng = np.random.RandomState(11)
    crit = nn.CTCLoss(blank=0)
    loss = crit(T(rng.randn(5, 1, 4).astype(np.float32)),
                T(np.asarray([[1, 2]], np.int32)),
                T(np.asarray([5], np.int64)),
                T(np.asarray([2], np.int64)))
    assert np.isfinite(float(loss.item()))


def _np_edit_distance(a, b):
    dp = np.zeros((len(a) + 1, len(b) + 1))
    dp[:, 0] = np.arange(len(a) + 1)
    dp[0, :] = np.arange(len(b) + 1)
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            dp[i, j] = min(dp[i - 1, j] + 1, dp[i, j - 1] + 1,
                           dp[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return dp[-1, -1]


def test_edit_distance_matches_numpy():
    a = np.asarray([[1, 2, 3, 4], [5, 6, 7, 0]], np.int64)
    b = np.asarray([[1, 3, 4, 0], [5, 6, 8, 2]], np.int64)
    a_len = np.asarray([4, 3], np.int64)
    b_len = np.asarray([3, 4], np.int64)
    d, n = F.edit_distance(T(a), T(b), normalized=False,
                           input_length=T(a_len), label_length=T(b_len))
    refs = [_np_edit_distance(a[i, :a_len[i]], b[i, :b_len[i]])
            for i in range(2)]
    np.testing.assert_allclose(d.numpy()[:, 0], refs)
    assert int(n.numpy()[0]) == 2
    dn, _ = F.edit_distance(T(a), T(b), normalized=True,
                            input_length=T(a_len), label_length=T(b_len))
    np.testing.assert_allclose(dn.numpy()[:, 0],
                               [refs[0] / 3.0, refs[1] / 4.0])


def test_edit_distance_ignored_tokens():
    a = np.asarray([[1, 9, 2, 3]], np.int64)
    b = np.asarray([[1, 2, 9, 3]], np.int64)
    d, _ = F.edit_distance(T(a), T(b), normalized=False,
                           ignored_tokens=[9])
    np.testing.assert_allclose(d.numpy()[:, 0], [0.0])


def test_beam_search_decode_greedy_consistency():
    """A deterministic 'LM' whose next-token logits depend only on the
    current token: beam search with K=1 must equal greedy argmax."""
    import jax.numpy as jnp
    from paddle_tpu.ops.decode import _beam_search

    V = 6
    rng = np.random.RandomState(12)
    table = jnp.asarray(rng.randn(V, V).astype(np.float32))

    def step_fn(tokens, state):
        return table[tokens], state

    seqs, scores = _beam_search(step_fn, {"d": jnp.zeros((2, 1))},
                                start_token=0, end_token=V - 1, K=1,
                                max_steps=5, V=V, length_penalty=0.0)
    # greedy rollout with end-token termination (finished lanes extend
    # with end_token, like the decoder's frozen lanes)
    t = 0
    ref = []
    tab = np.asarray(table)
    done = False
    for _ in range(5):
        if done:
            ref.append(V - 1)
            continue
        lsm = tab[t] - np.log(np.exp(tab[t] - tab[t].max()).sum()) \
            - tab[t].max()
        t = int(np.argmax(tab[t]))
        ref.append(t)
        if t == V - 1:
            done = True
    np.testing.assert_array_equal(np.asarray(seqs)[0, 0], ref)
    np.testing.assert_array_equal(np.asarray(seqs)[1, 0], ref)


def test_beam_search_wider_beam_finds_better_sequence():
    """Construct a trap: greedy takes a high-probability first step into
    a low-probability region; K=3 must find a total-log-prob sequence at
    least as good as K=1."""
    import jax.numpy as jnp
    from paddle_tpu.ops.decode import _beam_search

    V = 4
    table = np.full((V, V), -5.0, np.float32)
    table[0, 1] = 2.0   # greedy first step
    table[1] = -8.0     # then it's stuck
    table[0, 2] = 1.5   # slightly worse first step...
    table[2, 3] = 3.0   # ...much better continuation
    tj = jnp.asarray(table)

    def step_fn(tokens, state):
        return tj[tokens], state

    def best_score(K):
        seqs, scores = _beam_search(step_fn, {"d": jnp.zeros((1, 1))},
                                    start_token=0, end_token=V - 1, K=K,
                                    max_steps=2, V=V, length_penalty=0.0)
        return float(np.asarray(scores)[0, 0])

    assert best_score(3) >= best_score(1)
    assert best_score(3) > best_score(1) + 0.5  # the trap is real


def test_beam_search_decoder_layer_api():
    """nn.BeamSearchDecoder + dynamic_decode over an LSTMCell runs and
    returns well-formed, best-first sorted beams."""
    import paddle_tpu.nn as nn

    paddle.seed(0)
    V, H, B, K = 7, 8, 2, 3
    cell = nn.LSTMCell(H, H)
    emb = nn.Embedding(V, H)
    proj = nn.Linear(H, V)
    decoder = nn.BeamSearchDecoder(cell, start_token=0, end_token=V - 1,
                                   beam_size=K, embedding_fn=emb,
                                   output_fn=proj)
    h0 = paddle.zeros([B, H])
    c0 = paddle.zeros([B, H])
    (seqs, scores), final = nn.dynamic_decode(decoder, inits=(h0, c0),
                                              max_step_num=4)
    assert seqs.shape == [B, K, 4]
    s = scores.numpy()
    assert (np.diff(s, axis=1) <= 1e-5).all()  # sorted best-first
    assert np.isfinite(s[:, 0]).all()


# -- r4 straggler ops: matrix_nms, renorm, op-level beam_search --------------

def _np_matrix_nms(bboxes, scores, score_threshold, post_threshold,
                   nms_top_k, keep_top_k, use_gaussian, sigma,
                   background_label, normalized):
    """Literal numpy transcription of matrix_nms_op.cc:81-150."""
    N, C, M = scores.shape
    norm = 0.0 if normalized else 1.0

    def iou(a, b):
        aa = (a[2] - a[0] + norm) * (a[3] - a[1] + norm)
        ab = (b[2] - b[0] + norm) * (b[3] - b[1] + norm)
        x1, y1 = max(a[0], b[0]), max(a[1], b[1])
        x2, y2 = min(a[2], b[2]), min(a[3], b[3])
        inter = max(x2 - x1 + norm, 0.0) * max(y2 - y1 + norm, 0.0)
        return inter / (aa + ab - inter) if inter > 0 else 0.0

    outs, counts = [], []
    for n in range(N):
        rows = []
        for c in range(C):
            if c == background_label:
                continue
            sc = scores[n, c]
            order = np.argsort(-sc)[:nms_top_k if nms_top_k > 0 else M]
            s = sc[order]
            b = bboxes[n][order]
            kk = len(order)
            max_iou = np.zeros(kk)
            ious = np.zeros((kk, kk))
            for j in range(1, kk):
                for i in range(j):
                    ious[j, i] = iou(b[j], b[i])
                max_iou[j] = ious[j, :j].max() if j else 0.0
            for j in range(kk):
                if s[j] <= score_threshold:
                    continue
                decay = 1.0
                for i in range(j):
                    if use_gaussian:
                        d = np.exp((max_iou[i] ** 2 - ious[j, i] ** 2)
                                   * sigma)
                    else:
                        d = (1 - ious[j, i]) / (1 - max_iou[i])
                    decay = min(decay, d)
                ds = s[j] * decay
                if ds > post_threshold:
                    rows.append([c, ds] + list(b[j]))
        rows.sort(key=lambda r: -r[1])
        if keep_top_k > 0:
            rows = rows[:keep_top_k]
        outs.append(rows)
        counts.append(len(rows))
    return outs, counts


def test_matrix_nms_matches_cc_reference():
    from paddle_tpu.vision.ops import matrix_nms

    rng = np.random.RandomState(0)
    N, C, M = 2, 3, 12
    centers = rng.rand(N, M, 2) * 50
    wh = rng.rand(N, M, 2) * 20 + 4
    bboxes = np.concatenate([centers - wh / 2, centers + wh / 2],
                            axis=-1).astype(np.float32)
    scores = rng.rand(N, C, M).astype(np.float32)

    for use_gaussian in (False, True):
        out, num = matrix_nms(
            paddle.to_tensor(bboxes), paddle.to_tensor(scores),
            score_threshold=0.3, post_threshold=0.2, nms_top_k=8,
            keep_top_k=6, use_gaussian=use_gaussian,
            gaussian_sigma=2.0, background_label=0)
        ref_rows, ref_counts = _np_matrix_nms(
            bboxes, scores, 0.3, 0.2, 8, 6, use_gaussian, 2.0, 0, True)
        got = np.asarray(out._value)
        cnt = np.asarray(num._value)
        np.testing.assert_array_equal(cnt, ref_counts)
        for n in range(N):
            rows = got[n]
            live = rows[rows[:, 0] >= 0]
            ref = np.asarray(ref_rows[n], np.float32).reshape(-1, 6)
            np.testing.assert_allclose(live, ref, rtol=1e-4,
                                       atol=1e-5)


def test_renorm_matches_numpy():
    import paddle_tpu.ops.math as m

    rng = np.random.RandomState(1)
    x = rng.randn(4, 5, 6).astype(np.float32) * 3
    for p, axis, mx in ((2.0, 1, 2.0), (1.0, 0, 5.0), (2.0, -1, 1.0)):
        out = np.asarray(m.renorm(paddle.to_tensor(x), p, axis,
                                  mx)._value)
        ax = axis % 3
        red = tuple(i for i in range(3) if i != ax)
        norms = (np.abs(x) ** p).sum(axis=red, keepdims=True) ** (1 / p)
        factor = np.where(norms > mx, mx / norms, 1.0)
        np.testing.assert_allclose(out, x * factor, rtol=1e-5,
                                   atol=1e-6)
    # sub-tensors under the bound untouched
    small = np.full((2, 2), 0.1, np.float32)
    np.testing.assert_allclose(
        np.asarray(m.renorm(paddle.to_tensor(small), 2.0, 0,
                            10.0)._value), small)


def test_renorm_gradient():
    import paddle_tpu.ops.math as m

    x = paddle.to_tensor(np.asarray([[3.0, 4.0]], np.float32))
    x.stop_gradient = False
    out = m.renorm(x, 2.0, 0, 1.0)  # norm 5 -> scaled by 1/5
    np.testing.assert_allclose(np.asarray(out._value),
                               [[0.6, 0.8]], rtol=1e-6)
    paddle.sum(out).backward()
    assert np.isfinite(np.asarray(x.grad._value)).all()


def test_beam_search_op_level_step():
    """beam_search_op.cc raw-op parity: one step over [batch*beam, V]
    accumulated scores; numpy reference does the per-batch-group
    beam*V top-k."""
    from paddle_tpu.ops.decode import beam_search

    batch, beam, V = 2, 3, 7
    rng = np.random.RandomState(2)
    pre_ids = rng.randint(1, V, (batch * beam, 1)).astype(np.int64)
    pre_scores = rng.randn(batch * beam, 1).astype(np.float32)
    scores = rng.randn(batch * beam, V).astype(np.float32)

    sel_ids, sel_scores, parent = beam_search(
        paddle.to_tensor(pre_ids), paddle.to_tensor(pre_scores),
        None, paddle.to_tensor(scores), beam_size=beam, end_id=0)

    acc = scores.reshape(batch, beam, V)
    for b in range(batch):
        flat = acc[b].reshape(-1)
        top = np.argsort(-flat)[:beam]
        np.testing.assert_allclose(
            np.asarray(sel_scores._value).reshape(batch, beam)[b],
            flat[top], rtol=1e-6)
        np.testing.assert_array_equal(
            np.asarray(sel_ids._value).reshape(batch, beam)[b],
            top % V)
        np.testing.assert_array_equal(
            np.asarray(parent._value).reshape(batch, beam)[b],
            top // V + b * beam)


def test_beam_search_finished_lanes_emit_end_id():
    from paddle_tpu.ops.decode import beam_search

    beam, V = 2, 5
    end_id = 0
    pre_ids = np.asarray([[end_id], [3]], np.int64)  # lane 0 finished
    pre_scores = np.asarray([[-1.0], [-2.0]], np.float32)
    scores = np.full((2, V), -10.0, np.float32)
    scores[1, 4] = 5.0  # live lane strongly prefers token 4; its
    # other candidates (-10) lose to the finished lane's -1
    sel_ids, sel_scores, parent = beam_search(
        paddle.to_tensor(pre_ids), paddle.to_tensor(pre_scores),
        None, paddle.to_tensor(scores), beam_size=beam, end_id=end_id)
    ids = np.asarray(sel_ids._value).ravel()
    # the finished lane survives ONLY as end_id with its old score
    assert 0 in ids and 4 in ids
    i0 = list(ids).index(0)
    np.testing.assert_allclose(
        np.asarray(sel_scores._value).ravel()[i0], -1.0)


def test_beam_search_gathers_through_ids():
    """Reference composition topk -> beam_search: scores are the
    [batch*beam, K] top-k slice and `ids` carries the vocab ids the
    columns stand for — selected tokens must gather THROUGH ids."""
    from paddle_tpu.ops.decode import beam_search

    beam = 2
    probs = np.asarray([[0.1, 0.0, 0.6, 0.3, 0.0],
                        [0.0, 0.5, 0.0, 0.1, 0.4],
                        [0.2, 0.2, 0.2, 0.3, 0.1],
                        [0.7, 0.0, 0.1, 0.1, 0.1]], np.float32)
    k = 2
    top_ids = np.argsort(-probs, axis=1)[:, :k]
    top_scores = np.take_along_axis(probs, top_ids, axis=1)
    pre_ids = np.full((4, 1), 9, np.int64)  # none finished
    pre_scores = np.zeros((4, 1), np.float32)
    sel_ids, sel_scores, parent = beam_search(
        paddle.to_tensor(pre_ids), paddle.to_tensor(pre_scores),
        paddle.to_tensor(top_ids.astype(np.int64)),
        paddle.to_tensor(top_scores), beam_size=beam, end_id=0)
    ids = np.asarray(sel_ids._value).reshape(2, beam)
    par = np.asarray(parent._value).reshape(2, beam)
    # group 0 (rows 0,1): best candidates are vocab 2 (0.6, row 0)
    # and vocab 1 (0.5, row 1) — VOCAB ids, not top-k positions
    np.testing.assert_array_equal(ids[0], [2, 1])
    np.testing.assert_array_equal(par[0], [0, 1])
    # group 1 (rows 2,3): vocab 0 (0.7, row 3), vocab 3 (0.3, row 2)
    np.testing.assert_array_equal(ids[1], [0, 3])
    np.testing.assert_array_equal(par[1], [3, 2])
