"""nn.Layer system + layers (reference tests: test_layers.py,
test_imperative_* family)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_shapes_and_grad():
    lin = nn.Linear(8, 4)
    x = paddle.randn([2, 8])
    y = lin(x)
    assert y.shape == [2, 4]
    y.sum().backward()
    assert lin.weight.grad is not None
    assert lin.weight.grad.shape == [8, 4]
    assert lin.bias.grad.shape == [4]


def test_layer_registry():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 4)
            self.fc2 = nn.Linear(4, 2)

        def forward(self, x):
            return self.fc2(self.fc1(x))

    net = Net()
    params = net.parameters()
    assert len(params) == 4
    names = [n for n, _ in net.named_parameters()]
    assert "fc1.weight" in names and "fc2.bias" in names
    subs = net.sublayers()
    assert len(subs) == 2


def test_state_dict_roundtrip():
    net = nn.Linear(3, 3)
    sd = net.state_dict()
    net2 = nn.Linear(3, 3)
    net2.set_state_dict(sd)
    np.testing.assert_allclose(net2.weight.numpy(), net.weight.numpy())


def test_train_eval_dropout():
    d = nn.Dropout(0.5)
    x = paddle.ones([100])
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), np.ones(100))
    d.train()
    out = d(x).numpy()
    assert (out == 0).any()
    # upscale keeps expectation
    assert abs(out.mean() - 1.0) < 0.35


def test_sequential_and_layerlist():
    seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([3, 4])
    assert seq(x).shape == [3, 2]
    assert len(seq) == 3
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll.parameters()) == 6


def test_conv_bn_pool_stack():
    net = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1),
        nn.BatchNorm2D(8),
        nn.ReLU(),
        nn.MaxPool2D(2, 2),
    )
    x = paddle.randn([2, 3, 8, 8])
    y = net(x)
    assert y.shape == [2, 8, 4, 4]
    y.sum().backward()


def test_batchnorm_running_stats():
    bn = nn.BatchNorm2D(4, momentum=0.5)
    x = paddle.randn([8, 4, 3, 3]) * 2.0 + 1.0
    bn.train()
    bn(x)
    assert not np.allclose(bn._mean.numpy(), np.zeros(4))
    bn.eval()
    m = bn._mean.numpy().copy()
    bn(x)
    np.testing.assert_allclose(bn._mean.numpy(), m)  # frozen in eval


def test_embedding():
    emb = nn.Embedding(10, 4)
    ids = paddle.to_tensor(np.asarray([[1, 2], [3, 4]], np.int64))
    out = emb(ids)
    assert out.shape == [2, 2, 4]
    out.sum().backward()
    assert emb.weight.grad is not None


def test_layernorm_layer():
    ln = nn.LayerNorm(16)
    x = paddle.randn([4, 16])
    y = ln(x)
    out = y.numpy()
    np.testing.assert_allclose(out.mean(-1), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(out.std(-1), np.ones(4), atol=1e-2)


def test_losses():
    ce = nn.CrossEntropyLoss()
    logits = paddle.randn([4, 5])
    labels = paddle.to_tensor(np.asarray([0, 1, 2, 3], np.int64))
    loss = ce(logits, labels)
    assert loss.shape == []
    mse = nn.MSELoss()
    a, b = paddle.randn([3]), paddle.randn([3])
    np.testing.assert_allclose(mse(a, b).numpy(),
                               ((a.numpy() - b.numpy()) ** 2).mean(),
                               rtol=1e-5)


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 6, 16])
    out = mha(x, x, x)
    assert out.shape == [2, 6, 16]
    out.sum().backward()


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 5, 16])
    out = enc(x)
    assert out.shape == [2, 5, 16]


def test_lstm():
    lstm = nn.LSTM(8, 16, num_layers=2)
    x = paddle.randn([4, 5, 8])
    out, (h, c) = lstm(x)
    assert out.shape == [4, 5, 16]
    assert h.shape == [2, 4, 16]
    out.sum().backward()


def test_gru_bidirect():
    gru = nn.GRU(8, 16, direction="bidirect")
    x = paddle.randn([2, 5, 8])
    out, h = gru(x)
    assert out.shape == [2, 5, 32]


def test_hooks():
    lin = nn.Linear(2, 2)
    calls = []
    h = lin.register_forward_post_hook(
        lambda lay, inp, out: calls.append(1))
    lin(paddle.randn([1, 2]))
    assert calls == [1]
    h.remove()
    lin(paddle.randn([1, 2]))
    assert calls == [1]


def test_clip_grad_by_global_norm():
    lin = nn.Linear(4, 4)
    x = paddle.randn([8, 4])
    (lin(x) * 100.0).sum().backward()
    clip = nn.ClipGradByGlobalNorm(1.0)
    pg = [(p, p.grad) for p in lin.parameters()]
    clipped = clip(pg)
    total = sum(float((g.numpy() ** 2).sum()) for _, g in clipped)
    assert total <= 1.01


def test_initializers():
    from paddle_tpu.nn.initializer import (Constant, KaimingNormal, Normal,
                                           XavierUniform)

    lin = nn.Linear(100, 50,
                    weight_attr=paddle.nn.ParamAttr(
                        initializer=XavierUniform()))
    w = lin.weight.numpy()
    limit = np.sqrt(6 / 150)
    assert np.abs(w).max() <= limit + 1e-6
    lin2 = nn.Linear(10, 10, weight_attr=paddle.nn.ParamAttr(
        initializer=Constant(0.5)))
    np.testing.assert_allclose(lin2.weight.numpy(), np.full((10, 10), 0.5))


def test_functional_interpolate():
    x = paddle.randn([1, 3, 4, 4])
    y = F.interpolate(x, scale_factor=2, mode="nearest")
    assert y.shape == [1, 3, 8, 8]
    z = F.interpolate(x, size=[2, 2], mode="bilinear")
    assert z.shape == [1, 3, 2, 2]
