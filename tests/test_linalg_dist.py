"""paddle.linalg.dist — SUMMA-style distributed linear algebra on the
8-device MULTICHIP mesh (ISSUE 12).

Gates: numerical agreement of SUMMA matmul / blocked Cholesky / TSQR
/ Lanczos / subspace iteration with the single-device jnp.linalg
reference, comm/<op>/bytes telemetry matching each algorithm's
analytic collective volume, PTA05x lint behavior on ShardedMatrix
specs (zero findings under PADDLE_SANITIZE=sharding for valid
layouts), the linalg_dispatch chaos site, persistent-compile-cache
integration, and the README doc-drift gate over linalg/."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle  # noqa: F401 — registers ops/backends
from paddle_tpu.core import monitor as cmon
from paddle_tpu.distributed import build_mesh, get_mesh, set_mesh
from paddle_tpu.linalg import dist as dla

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RNG = np.random.default_rng(12345)


def _f32(*shape):
    return RNG.standard_normal(shape).astype(np.float32)


def _spd(n):
    m = RNG.standard_normal((n, n))
    return (m @ m.T + n * np.eye(n)).astype(np.float32)


@pytest.fixture
def mesh24():
    prev = get_mesh()
    mesh = build_mesh({"dp": 2, "mp": 4})
    set_mesh(mesh)
    yield mesh
    set_mesh(prev)
    dla.clear_program_cache()


@pytest.fixture
def mesh42():
    prev = get_mesh()
    mesh = build_mesh({"dp": 4, "mp": 2})
    set_mesh(mesh)
    yield mesh
    set_mesh(prev)
    dla.clear_program_cache()


@pytest.fixture
def mesh1d():
    prev = get_mesh()
    mesh = build_mesh({"dp": 8})
    set_mesh(mesh)
    yield mesh
    set_mesh(prev)
    dla.clear_program_cache()


# ---------------------------------------------------------------------------
# ShardedMatrix layouts + lints
# ---------------------------------------------------------------------------

def test_shard_gather_roundtrip_blocks(mesh24):
    a = _f32(64, 32)
    A = dla.shard(a)
    assert A.shape == (64, 32)
    assert A.block_shape == (32, 8)
    assert A.layout == "blocks"
    assert tuple(A.spec) == ("dp", "mp")
    np.testing.assert_array_equal(A.gather(), a)
    # the global array is genuinely 2D-block-sharded over all devices
    assert len({d for s in A.value.addressable_shards
                for d in [s.device]}) == 8
    assert A.value.addressable_shards[0].data.shape == (32, 8)


def test_shard_gather_roundtrip_rows(mesh24):
    a = _f32(64, 4)
    A = dla.shard(a, layout="rows")
    assert A.block_shape == (8, 4)
    spec = tuple(A.spec)
    assert spec[0] == ("dp", "mp") and spec[1] is None
    np.testing.assert_array_equal(A.gather(), a)


def test_shard_rejects_non_2d_and_indivisible(mesh24):
    with pytest.raises(ValueError, match="2D"):
        dla.shard(_f32(4, 4, 4))
    with pytest.raises(ValueError, match="PTA051"):
        dla.shard(_f32(63, 32))  # rows not divisible by dp=2
    with pytest.raises(ValueError, match="PTA051"):
        dla.shard(_f32(64, 30))  # cols not divisible by mp=4
    with pytest.raises(ValueError, match="PTA051"):
        dla.shard(_f32(62, 4), layout="rows")  # 62 % 8 != 0


def test_grid_resolution_and_overrides(mesh24):
    g = dla.grid()
    assert (g.rx, g.cx, g.px, g.py) == ("dp", "mp", 2, 4)
    g = dla.grid(row_axis="mp", col_axis="dp")
    assert (g.px, g.py) == (4, 2)
    with pytest.raises(ValueError, match="not a mesh axis"):
        dla.grid(row_axis="nope")
    with pytest.raises(ValueError, match="distinct"):
        dla.grid(row_axis="dp", col_axis="dp")
    os.environ["PADDLE_LINALG_AXES"] = "mp,dp"
    try:
        g = dla.grid()
        assert (g.rx, g.cx) == ("mp", "dp")
    finally:
        del os.environ["PADDLE_LINALG_AXES"]


def test_lint_spec_records_findings_only_when_armed(mesh24):
    """PTA05x runs on every ShardedMatrix spec before compile: errors
    always raise; the analysis counters only move when the sanitizer
    (or PADDLE_ANALYSIS) is armed — the disarmed path must stay
    counter-clean (bench provenance contract)."""
    from paddle_tpu.monitor import sanitize as san

    cmon.stat_reset("analysis/PTA051/findings")
    with pytest.raises(ValueError):
        dla.shard(_f32(63, 32))
    assert cmon.stat_get("analysis/PTA051/findings") == 0
    san.configure("sharding")
    try:
        with pytest.raises(ValueError):
            dla.shard(_f32(63, 32))
        assert cmon.stat_get("analysis/PTA051/findings") >= 1
    finally:
        san.disarm()
        cmon.stat_reset("analysis/PTA051/findings")


# ---------------------------------------------------------------------------
# SUMMA matmul
# ---------------------------------------------------------------------------

def _matmul_case(M, K, N, block_size=None):
    a, b = _f32(M, K), _f32(K, N)
    C = dla.matmul(dla.shard(a), dla.shard(b), block_size=block_size)
    ref = a @ b
    np.testing.assert_allclose(C.gather(), ref, rtol=2e-4, atol=2e-4)
    return C


def test_summa_matches_reference_2x4(mesh24):
    C = _matmul_case(64, 96, 48)
    assert C.block_shape == (32, 12)


def test_summa_matches_reference_4x2(mesh42):
    _matmul_case(32, 64, 80)


def test_summa_matches_reference_1d(mesh1d):
    _matmul_case(64, 64, 32)


def test_summa_block_sizes_agree(mesh24):
    a, b = _f32(32, 96, ), _f32(96, 32)
    A, B = dla.shard(a), dla.shard(b)
    outs = [dla.matmul(A, B, block_size=nb).gather()
            for nb in (4, 12, 24)]
    for o in outs[1:]:
        np.testing.assert_allclose(o, outs[0], rtol=2e-4, atol=2e-4)
    with pytest.raises(ValueError, match="block_size"):
        dla.matmul(A, B, block_size=5)


def test_summa_shape_and_layout_validation(mesh24):
    A = dla.shard(_f32(64, 32))
    with pytest.raises(ValueError, match="inner dims"):
        dla.matmul(A, dla.shard(_f32(64, 32)))
    with pytest.raises(TypeError, match="ShardedMatrix"):
        dla.matmul(A, _f32(32, 8))
    with pytest.raises(ValueError, match="layout"):
        dla.matmul(A, dla.shard(_f32(32, 8), layout="rows"))


def test_summa_comm_bytes_match_analytic_volume(mesh24):
    """The acceptance gate: comm/broadcast/bytes must price exactly
    the SUMMA panel traffic — T panels x (A panel (M/px, nb) + B
    panel (nb, N/py)) f32 elements, counted at trace time."""
    M, K, N, nb = 64, 32, 64, 8
    a, b = _f32(M, K), _f32(K, N)
    A, B = dla.shard(a), dla.shard(b)
    grid = A.grid
    dla.clear_program_cache()
    before = cmon.stat_get("comm/broadcast/bytes")
    calls_before = cmon.stat_get("comm/broadcast/calls")
    dla.matmul(A, B, block_size=nb)
    t = K // nb
    expect = t * (M // grid.px * nb + nb * N // grid.py) * 4
    assert cmon.stat_get("comm/broadcast/bytes") - before == expect
    assert cmon.stat_get("comm/broadcast/calls") - calls_before == 2 * t


def test_summa_counters_and_flight(mesh24):
    from paddle_tpu.monitor import flight

    a, b = _f32(16, 16), _f32(16, 16)
    A, B = dla.shard(a), dla.shard(b)
    before = cmon.stat_get("linalg/matmuls")
    bytes_before = cmon.stat_get("linalg/bytes")
    dla.matmul(A, B)
    assert cmon.stat_get("linalg/matmuls") == before + 1
    assert cmon.stat_get("linalg/bytes") > bytes_before
    kinds = [e["kind"] for e in flight.tail()]
    assert "linalg_begin" in kinds and "linalg_end" in kinds


# ---------------------------------------------------------------------------
# block-size selection
# ---------------------------------------------------------------------------

def test_block_candidates_and_env_pin(mesh24):
    A, B = dla.shard(_f32(32, 96)), dla.shard(_f32(96, 32))
    g = A.grid
    cands = dla.block_candidates(96, g)
    # gcd(96/2, 96/4) = 24
    assert cands[0] == 24 and all(24 % c == 0 for c in cands)
    os.environ["PADDLE_LINALG_BLOCK"] = "12"
    try:
        assert dla.choose_block_size(A, B) == 12
        os.environ["PADDLE_LINALG_BLOCK"] = "7"
        with pytest.raises(ValueError, match="PADDLE_LINALG_BLOCK"):
            dla.choose_block_size(A, B)
    finally:
        del os.environ["PADDLE_LINALG_BLOCK"]
    assert dla.choose_block_size(A, B) == 24  # largest capped divisor


def test_block_autotune_rides_cost_model(mesh24):
    """PADDLE_LINALG_AUTOTUNE=1 profiles candidate programs through
    cost_model.CostModel and caches the pick per shape family."""
    from paddle_tpu.linalg.dist import summa

    A, B = dla.shard(_f32(16, 32)), dla.shard(_f32(32, 16))
    summa._chosen.clear()
    os.environ["PADDLE_LINALG_AUTOTUNE"] = "1"
    try:
        nb = dla.choose_block_size(A, B)
        assert nb in dla.block_candidates(32, A.grid)
        assert summa._chosen  # cached for the rerun
        assert dla.choose_block_size(A, B) == nb
        out = dla.matmul(A, B, block_size=nb)
        np.testing.assert_allclose(
            out.gather(), A.gather() @ B.gather(),
            rtol=2e-4, atol=2e-4)
    finally:
        del os.environ["PADDLE_LINALG_AUTOTUNE"]
        summa._chosen.clear()


# ---------------------------------------------------------------------------
# factorizations
# ---------------------------------------------------------------------------

def test_cholesky_matches_reference(mesh24):
    spd = _spd(64)
    L = dla.cholesky(dla.shard(spd))
    ref = np.linalg.cholesky(spd)
    np.testing.assert_allclose(L.gather(), ref, rtol=1e-3, atol=1e-3)
    # strictly lower-triangular blocks everywhere above the diagonal
    assert np.allclose(L.gather(), np.tril(L.gather()))


def test_cholesky_block_sizes_and_4x2(mesh42):
    spd = _spd(64)
    ref = np.linalg.cholesky(spd)
    for nb in (8, 16):
        L = dla.cholesky(dla.shard(spd), block_size=nb)
        np.testing.assert_allclose(L.gather(), ref, rtol=1e-3,
                                   atol=1e-3)
    with pytest.raises(ValueError, match="block_size"):
        dla.cholesky(dla.shard(spd), block_size=5)
    with pytest.raises(ValueError, match="square"):
        dla.cholesky(dla.shard(_f32(64, 32)))


def test_cholesky_comm_bytes_match_analytic_volume(mesh24):
    """Per panel: one (nb,nb) 2D broadcast of the diagonal block, one
    (N/px, nb) row broadcast of the panel, one (N/px, nb) all_gather
    up the column tree. all_gather prices its FULL payload — the
    group_size gathered copies, px * the per-rank (N/px, nb) panel =
    the whole (N, nb) column per panel (the ISSUE-14 list-arg payload
    fix; broadcast stays the per-rank tensor)."""
    N, nb = 64, 16
    spd = _spd(N)
    A = dla.shard(spd)
    g = A.grid
    dla.clear_program_cache()
    b0 = cmon.stat_get("comm/broadcast/bytes")
    g0 = cmon.stat_get("comm/all_gather/bytes")
    dla.cholesky(A, block_size=nb)
    t = N // nb
    rb = N // g.px
    assert cmon.stat_get("comm/broadcast/bytes") - b0 == \
        t * (nb * nb + rb * nb) * 4
    assert cmon.stat_get("comm/all_gather/bytes") - g0 == \
        t * g.px * rb * nb * 4


def test_tsqr_matches_reference(mesh24):
    a = _f32(256, 8)
    Q, R = dla.qr(dla.shard(a, layout="rows"))
    qg = Q.gather()
    np.testing.assert_allclose(qg @ R, a, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(qg.T @ qg, np.eye(8), atol=1e-4)
    assert np.allclose(R, np.triu(R))
    # against the single-device reference, both sign-normalized to
    # diag(R) >= 0
    qr_ref, r_ref = np.linalg.qr(a)
    s = np.sign(np.diag(r_ref))
    s[s == 0] = 1
    np.testing.assert_allclose(R, r_ref * s[:, None], rtol=1e-3,
                               atol=1e-3)
    np.testing.assert_allclose(qg, qr_ref * s[None, :], rtol=1e-3,
                               atol=1e-3)


def test_tsqr_validation_and_counters(mesh24):
    with pytest.raises(ValueError, match="rows"):
        dla.qr(dla.shard(_f32(64, 8)))
    with pytest.raises(ValueError, match="at least as tall"):
        dla.qr(dla.shard(_f32(64, 16), layout="rows"))  # 8 rows < 16
    before = cmon.stat_get("linalg/factorizations")
    dla.qr(dla.shard(_f32(64, 4), layout="rows"))
    assert cmon.stat_get("linalg/factorizations") == before + 1


# ---------------------------------------------------------------------------
# eigensolvers
# ---------------------------------------------------------------------------

def test_matvec_matches_reference(mesh24):
    a, v = _spd(64), _f32(64)
    A = dla.shard(a)
    w = np.asarray(dla.matvec(A, v))
    np.testing.assert_allclose(w, a @ v, rtol=2e-4, atol=2e-4)
    vk = _f32(64, 3)
    np.testing.assert_allclose(np.asarray(dla.matvec(A, vk)), a @ vk,
                               rtol=2e-4, atol=2e-4)
    with pytest.raises(ValueError, match="length"):
        dla.matvec(A, _f32(32))


def test_lanczos_extreme_eigenvalues(mesh24):
    sym = _spd(64)
    ref = np.linalg.eigvalsh(sym)
    top = dla.lanczos(dla.shard(sym), k=2, iters=40)
    np.testing.assert_allclose(top, ref[::-1][:2], rtol=1e-3)
    bot = dla.lanczos(dla.shard(sym), k=1, iters=40,
                      which="smallest")
    np.testing.assert_allclose(bot, ref[:1], rtol=1e-2)
    with pytest.raises(ValueError, match="which"):
        dla.lanczos(dla.shard(sym), which="middle")


def test_eigsh_subspace_iteration(mesh24):
    sym = _spd(64)
    wr, vr = np.linalg.eigh(sym)
    w, V = dla.eigsh(dla.shard(sym), k=3, iters=50, seed=3)
    np.testing.assert_allclose(w, wr[::-1][:3], rtol=1e-3)
    # eigenvector residual ||A v - w v|| small, sign-agnostic
    res = sym @ V - V * w[None, :]
    assert np.abs(res).max() < 5e-2
    before = cmon.stat_get("linalg/eigensolves")
    dla.eigsh(dla.shard(sym), k=2, iters=10)
    assert cmon.stat_get("linalg/eigensolves") == before + 1


# ---------------------------------------------------------------------------
# production spine: sanitizer, chaos, compile cache
# ---------------------------------------------------------------------------

def test_algorithms_sanitize_clean(mesh24):
    """Acceptance: zero sanitizer findings under
    PADDLE_SANITIZE=sharding while every algorithm family runs."""
    from paddle_tpu.monitor import sanitize as san

    san.configure("sharding")
    try:
        cmon.registry.reset_all()
        spd = _spd(32)
        A = dla.shard(spd)
        dla.matmul(A, A)
        dla.cholesky(A)
        dla.qr(dla.shard(_f32(64, 4), layout="rows"))
        dla.lanczos(A, k=1, iters=8)
        findings = {k: v for k, v in cmon.registry.snapshot().items()
                    if k.startswith("analysis/PTA05")}
        assert not any(findings.values()), findings
    finally:
        san.disarm()


def test_chaos_linalg_dispatch_site(mesh24):
    from paddle_tpu.monitor import chaos

    A = dla.shard(_f32(16, 16))
    with chaos.inject("linalg_dispatch", "raise") as rule:
        with pytest.raises(chaos.ChaosInjected):
            dla.matmul(A, A)
        assert rule.triggers == 1
    # disarmed again: the same cached program dispatches clean
    out = dla.matmul(A, A)
    np.testing.assert_allclose(out.gather(),
                               A.gather() @ A.gather(),
                               rtol=2e-4, atol=2e-4)


def test_persistent_compile_cache_warm_hit(mesh24, tmp_path):
    """A dist program lowered once lands in the persistent cache; a
    fresh in-process program cache then boots from a warm hit."""
    a, b = _f32(32, 32), _f32(32, 16)
    prev = os.environ.get("PADDLE_COMPILE_CACHE_DIR")
    os.environ["PADDLE_COMPILE_CACHE_DIR"] = str(tmp_path)
    try:
        dla.clear_program_cache()
        misses0 = cmon.stat_get("jit/persistent_cache/misses")
        c1 = dla.matmul(dla.shard(a), dla.shard(b))
        assert cmon.stat_get("jit/persistent_cache/misses") > misses0
        dla.clear_program_cache()
        hits0 = cmon.stat_get("jit/persistent_cache/hits")
        c2 = dla.matmul(dla.shard(a), dla.shard(b))
        assert cmon.stat_get("jit/persistent_cache/hits") > hits0
        np.testing.assert_array_equal(c1.gather(), c2.gather())
    finally:
        if prev is None:
            del os.environ["PADDLE_COMPILE_CACHE_DIR"]
        else:
            os.environ["PADDLE_COMPILE_CACHE_DIR"] = prev
        dla.clear_program_cache()


def test_program_cache_reuses_executables(mesh24):
    a, b = _f32(16, 32), _f32(32, 16)
    A, B = dla.shard(a), dla.shard(b)
    dla.clear_program_cache()
    compiles0 = cmon.stat_get("linalg/compiles")
    dla.matmul(A, B)
    assert cmon.stat_get("linalg/compiles") == compiles0 + 1
    hits0 = cmon.stat_get("linalg/program_cache/hits")
    dla.matmul(A, B)
    assert cmon.stat_get("linalg/compiles") == compiles0 + 1
    assert cmon.stat_get("linalg/program_cache/hits") == hits0 + 1


# ---------------------------------------------------------------------------
# API surface + doc drift
# ---------------------------------------------------------------------------

def test_linalg_package_surface_unchanged():
    """The package promotion must keep the ops.linalg surface: every
    op reachable at paddle.linalg.<op>, and the shadowed distance op
    still available as paddle.dist / linalg.pdist_op."""
    import paddle_tpu.linalg as L
    from paddle_tpu.ops import linalg as ops_linalg

    for name in ops_linalg.__all__:
        if name == "dist":
            continue  # the subpackage wins this name (ISSUE 12)
        assert getattr(L, name) is getattr(ops_linalg, name), name
    assert L.pdist_op is ops_linalg.dist
    assert callable(paddle.dist)
    import types

    assert isinstance(L.dist, types.ModuleType)
    assert L.dist is dla


def test_readme_documents_linalg_env_vars():
    """Doc-drift gate over linalg/: every PADDLE_* env var the
    package reads must appear in the README (the test_flight.py
    contract, extended over the new subsystem)."""
    import re

    pkg = os.path.join(REPO, "paddle_tpu", "linalg")
    vars_used = set()
    for root, _, files in os.walk(pkg):
        for f in files:
            if not f.endswith(".py"):
                continue
            with open(os.path.join(root, f)) as fh:
                vars_used |= set(re.findall(r"PADDLE_[A-Z0-9_]+",
                                            fh.read()))
    assert vars_used, "expected PADDLE_LINALG_* knobs in linalg/"
    with open(os.path.join(REPO, "README.md")) as f:
        doc = f.read()
    missing = sorted(v for v in vars_used if v not in doc)
    assert not missing, \
        f"linalg env vars missing from README: {missing}"
    for needle in ("Distributed linear algebra", "ShardedMatrix",
                   "linalg_dispatch", "SUMMA", "TSQR"):
        assert needle in doc, f"{needle!r} missing from README"
