"""paddle.onnx.export (reference: python/paddle/onnx/export.py) —
hand-rolled ONNX protobuf writer.

Validation without the onnx package: (1) `protoc --decode_raw` parses
the file (wire-format well-formedness); (2) an independent mini wire
decoder in this test reconstructs the graph and EXECUTES it with
numpy (Conv/MaxPool/Gemm/Relu/Flatten), matching the paddle forward —
encode/decode consistency plus semantic correctness of the lowering."""
import shutil
import struct
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


# -- minimal protobuf wire decoder ------------------------------------------

def _read_varint(buf, i):
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf):
    """Yields (field_number, wire_type, value)."""
    i = 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 2:
            n, i = _read_varint(buf, i)
            v = buf[i:i + n]
            i += n
        elif wire == 5:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        else:
            raise ValueError(f"wire type {wire}")
        yield field, wire, v


def _decode_tensor(buf):
    dims, dtype, name, raw = [], 1, "", b""
    for f, w, v in _fields(buf):
        if f == 1:
            dims.append(v)
        elif f == 2:
            dtype = v
        elif f == 8:
            name = v.decode()
        elif f == 9:
            raw = v
    np_dt = {1: np.float32, 7: np.int64, 6: np.int32}[dtype]
    return name, np.frombuffer(raw, np_dt).reshape(dims)


def _decode_attr(buf):
    name, out = "", None
    ints = []
    for f, w, v in _fields(buf):
        if f == 1:
            name = v.decode()
        elif f == 2:
            out = v          # float
        elif f == 3:
            out = v          # int
        elif f == 4:
            out = v.decode()
        elif f == 8:
            ints.append(v)
    return name, (ints if ints else out)


def _decode_node(buf):
    ins, outs, op_type, attrs = [], [], "", {}
    for f, w, v in _fields(buf):
        if f == 1:
            ins.append(v.decode())
        elif f == 2:
            outs.append(v.decode())
        elif f == 4:
            op_type = v.decode()
        elif f == 5:
            k, a = _decode_attr(v)
            attrs[k] = a
    return {"op": op_type, "in": ins, "out": outs, "attrs": attrs}


def _decode_model(path):
    buf = open(path, "rb").read()
    graph = None
    opset = None
    for f, w, v in _fields(buf):
        if f == 7:
            graph = v
        elif f == 8:
            for f2, _, v2 in _fields(v):
                if f2 == 2:
                    opset = v2
    nodes, inits, g_in, g_out = [], {}, [], []
    for f, w, v in _fields(graph):
        if f == 1:
            nodes.append(_decode_node(v))
        elif f == 5:
            n, arr = _decode_tensor(v)
            inits[n] = arr
        elif f == 11:
            g_in.append(v)
        elif f == 12:
            g_out.append(v)
    return {"nodes": nodes, "inits": inits, "opset": opset,
            "n_inputs": len(g_in), "n_outputs": len(g_out)}


# -- numpy executor for the decoded graph -----------------------------------

def _np_conv(x, w, b, strides, pads, group):
    t, l, bb, r = pads
    x = np.pad(x, ((0, 0), (0, 0), (t, bb), (l, r)))
    n, cin, h, wd = x.shape
    co, cig, kh, kw = w.shape
    sh, sw = strides
    oh = (h - kh) // sh + 1
    ow = (wd - kw) // sw + 1
    out = np.zeros((n, co, oh, ow), np.float32)
    for oc in range(co):
        for i in range(oh):
            for j in range(ow):
                patch = x[:, :, i * sh:i * sh + kh, j * sw:j * sw + kw]
                out[:, oc, i, j] = (patch * w[oc][None]).sum((1, 2, 3))
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out


def _np_maxpool(x, kernel, strides, pads):
    kh, kw = kernel
    sh, sw = strides
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    out = np.zeros((n, c, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            out[:, :, i, j] = x[:, :, i * sh:i * sh + kh,
                                j * sw:j * sw + kw].max((2, 3))
    return out


def _execute(model, feed):
    env = dict(model["inits"])
    env.update(feed)
    for nd in model["nodes"]:
        a = [env[n] if n else None for n in nd["in"]]
        at = nd["attrs"]
        if nd["op"] == "Conv":
            out = _np_conv(a[0], a[1], a[2] if len(a) > 2 else None,
                           at["strides"], at["pads"], at.get("group", 1))
        elif nd["op"] == "MaxPool":
            out = _np_maxpool(a[0], at["kernel_shape"], at["strides"],
                              at["pads"])
        elif nd["op"] == "Gemm":
            out = a[0] @ a[1] + a[2]
        elif nd["op"] == "MatMul":
            out = a[0] @ a[1]
        elif nd["op"] == "Relu":
            out = np.maximum(a[0], 0)
        elif nd["op"] == "Flatten":
            out = a[0].reshape(a[0].shape[0], -1)
        elif nd["op"] == "Softmax":
            e = np.exp(a[0] - a[0].max(-1, keepdims=True))
            out = e / e.sum(-1, keepdims=True)
        elif nd["op"] == "Add":
            out = a[0] + a[1]
        elif nd["op"] == "BatchNormalization":
            x, scale, b, mean, var = a[:5]
            eps = at.get("epsilon", 1e-5)
            shp = (1, -1) + (1,) * (x.ndim - 2)
            out = ((x - mean.reshape(shp))
                   / np.sqrt(var.reshape(shp) + eps)
                   * scale.reshape(shp) + b.reshape(shp))
        elif nd["op"] == "Reshape":
            out = a[0].reshape([int(d) for d in a[1]])
        else:
            raise NotImplementedError(nd["op"])
        env[nd["out"][0]] = out
    return env


def test_lenet_onnx_export_roundtrip(tmp_path):
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    path = paddle.onnx.export(net, str(tmp_path / "lenet"),
                              input_spec=[[1, 1, 28, 28]])
    assert path.endswith(".onnx")

    model = _decode_model(path)
    ops = [n["op"] for n in model["nodes"]]
    assert ops.count("Conv") == 2 and ops.count("Gemm") == 3
    assert "MaxPool" in ops and "Flatten" in ops
    assert model["opset"] == 13
    assert model["n_inputs"] == 1 and model["n_outputs"] == 1

    # execute the DECODED graph with numpy and compare to paddle
    rng = np.random.RandomState(0)
    x = rng.rand(1, 1, 28, 28).astype(np.float32)
    env = _execute(model, {"x0": x})
    got = env[model["nodes"][-1]["out"][0]]
    ref = np.asarray(net(paddle.to_tensor(x))._value)
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


def test_mlp_with_activations_exports(tmp_path):
    import paddle_tpu.nn.functional as F

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.l1 = nn.Linear(8, 16)
            self.l2 = nn.Linear(16, 4)

        def forward(self, x):
            return F.softmax(self.l2(F.relu(self.l1(x))), axis=-1)

    paddle.seed(1)
    net = MLP()
    path = paddle.onnx.export(net, str(tmp_path / "mlp"),
                              input_spec=[[2, 8]])
    model = _decode_model(path)
    ops = [n["op"] for n in model["nodes"]]
    assert ops == ["Gemm", "Relu", "Gemm", "Softmax"]
    x = np.random.RandomState(2).rand(2, 8).astype(np.float32)
    env = _execute(model, {"x0": x})
    got = env[model["nodes"][-1]["out"][0]]
    ref = np.asarray(net(paddle.to_tensor(x))._value)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_unsupported_op_raises_by_name(tmp_path):
    class Weird(nn.Layer):
        def forward(self, x):
            return paddle.cumsum(x, axis=1)

    with pytest.raises(NotImplementedError, match="cumsum"):
        paddle.onnx.export(Weird(), str(tmp_path / "w"),
                           input_spec=[[2, 3]])


@pytest.mark.skipif(shutil.which("protoc") is None,
                    reason="protoc not available")
def test_protoc_decodes_the_wire_format(tmp_path):
    """Independent well-formedness check: protoc --decode_raw parses
    the file and the op_type strings are visible."""
    net = nn.Linear(4, 2)
    path = paddle.onnx.export(net, str(tmp_path / "lin"),
                              input_spec=[[3, 4]])
    r = subprocess.run(["protoc", "--decode_raw"],
                       stdin=open(path, "rb"),
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "Gemm" in r.stdout
    assert "paddle_tpu" in r.stdout


def test_batchnorm_export_inference_form(tmp_path):
    """Review r4: BN lowers with ONNX input order [X, scale, B, mean,
    var], ONE output, and the running-stat buffers keep their
    CONCRETE values (tracing must not leak abstract values into
    initializers)."""
    paddle.seed(3)
    net = nn.Sequential(nn.Conv2D(3, 4, 3), nn.BatchNorm2D(4))
    net.eval()
    # give the running stats non-trivial values
    bn = net[1]
    x_warm = paddle.to_tensor(
        np.random.RandomState(5).rand(2, 3, 8, 8).astype(np.float32))
    net.train()
    net(x_warm)
    net.eval()
    ref_in = np.random.RandomState(6).rand(1, 3, 8, 8).astype(
        np.float32)
    ref = np.asarray(net(paddle.to_tensor(ref_in))._value)

    path = paddle.onnx.export(net, str(tmp_path / "bn"),
                              input_spec=[[1, 3, 8, 8]])
    model = _decode_model(path)
    bn_nodes = [n for n in model["nodes"]
                if n["op"] == "BatchNormalization"]
    assert len(bn_nodes) == 1 and len(bn_nodes[0]["out"]) == 1
    env = _execute(model, {"x0": ref_in})
    got = env[model["nodes"][-1]["out"][0]]
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)
    # buffers survived the export untouched (concrete)
    assert np.asarray(bn._mean._value if hasattr(bn, "_mean")
                      else bn.weight._value).dtype == np.float32


def test_3d_linear_lowers_to_matmul_add(tmp_path):
    """ONNX Gemm is 2-D only: N-D Linear inputs lower to
    MatMul + Add."""
    paddle.seed(4)
    net = nn.Linear(8, 4)
    path = paddle.onnx.export(net, str(tmp_path / "l3"),
                              input_spec=[[2, 5, 8]])
    model = _decode_model(path)
    ops = [n["op"] for n in model["nodes"]]
    assert ops == ["MatMul", "Add"], ops
    x = np.random.RandomState(7).rand(2, 5, 8).astype(np.float32)
    env = _execute(model, {"x0": x})
    got = env[model["nodes"][-1]["out"][0]]
    ref = np.asarray(net(paddle.to_tensor(x))._value)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_same_padding_maps_to_auto_pad(tmp_path):
    net = nn.Conv2D(3, 4, 3, padding="SAME")
    path = paddle.onnx.export(net, str(tmp_path / "sp"),
                              input_spec=[[1, 3, 8, 8]])
    model = _decode_model(path)
    conv = [n for n in model["nodes"] if n["op"] == "Conv"][0]
    assert conv["attrs"].get("auto_pad") == "SAME_UPPER"
    assert "pads" not in conv["attrs"]


def test_partial_flatten_lowers_to_reshape(tmp_path):
    class PartialFlat(nn.Layer):
        def forward(self, x):
            return paddle.flatten(x, start_axis=2, stop_axis=3)

    path = paddle.onnx.export(PartialFlat(), str(tmp_path / "pf"),
                              input_spec=[[2, 3, 4, 5]])
    model = _decode_model(path)
    ops = [n["op"] for n in model["nodes"]]
    assert ops == ["Reshape"], ops
    x = np.random.RandomState(8).rand(2, 3, 4, 5).astype(np.float32)
    env = _execute(model, {"x0": x})
    got = env[model["nodes"][-1]["out"][0]]
    assert got.shape == (2, 3, 20)
