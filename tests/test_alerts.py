"""ISSUE 20: SLO alerting & control plane.

Four rings:

  * Histogram windows — `delta_since` windowed deltas + the
    quantile sentinel edges alert evaluation hits between traffic
    waves (empty window, all-underflow, single bucket, counter
    reset, boundary mismatch).
  * Rule engine — spec grammar on the chaos/sanitize family, every
    rule kind's state machine via deterministic evaluate_once()
    ticks, the satellite-1 regression (a just-recorded flight gauge
    is visible to the next tick), /alertz, the `monitor alerts`
    CLI on the exit-2 contract.
  * Fleet rollup — `monitor fleet`/`scrape` any-rank-firing rollup
    over 3 synthetic rank spools (firing / resolved / never-armed),
    text + --json, partial-fleet exit-1 preserved.
  * Closed loop — the acceptance gate: chaos latency storm on a
    1-replica Router fires the TTFT alert, the Autoscaler spawns a
    second replica, the alert resolves and drains it back, tokens
    identical to the fault-free run, zero KV blocks leak fleet-wide;
    disarmed runs are thread-free and alerts/*-counter-clean
    (subprocess).
"""
import json
import math
import os
import socket
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor as cmon
from paddle_tpu.inference.serving import (Autoscaler, LLMEngine,
                                          Router, SamplingParams)
from paddle_tpu.monitor import alerts, chaos, flight
from paddle_tpu.monitor import server as mserver
from paddle_tpu.monitor.cli import main as cli_main
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_TOKENS = 6
PROMPT_LENS = (3, 9, 5, 12, 7, 4)


@pytest.fixture(autouse=True)
def _disarm_after():
    yield
    alerts.disarm()


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, ffn_hidden=128, max_seq_len=64,
                    dropout=0.0, use_flash_attention=False,
                    initializer_range=0.35)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(3)
    return [list(rng.randint(1, 128, n)) for n in PROMPT_LENS]


@pytest.fixture(scope="module")
def want(model, prompts):
    eng = LLMEngine(model, max_batch=4, block_size=8, num_blocks=32)
    outs = eng.generate(
        prompts, sampling=SamplingParams(max_new_tokens=N_TOKENS))
    assert eng.check_drained() == {}
    return outs


def sp(**kw):
    kw.setdefault("max_new_tokens", N_TOKENS)
    return SamplingParams(**kw)


def assert_no_leaks(router):
    from paddle_tpu.analysis.serving import audit_block_accounting

    assert router.check_drained() == {}
    for rep in router._replicas:
        eng = rep.engine
        live = [r.req_id for r in eng._requests.values()
                if not r.finished]
        rep_ = audit_block_accounting(eng.cache.allocator, live)
        assert rep_.findings == [], \
            [f.format() for f in rep_.findings]


# ---------------------------------------------------------------------------
# ring (a): Histogram.delta_since + quantile sentinels (satellite 2)
# ---------------------------------------------------------------------------

class TestDeltaSince:
    def test_windowed_delta_isolates_recent_observations(self):
        h = cmon.Histogram()
        for _ in range(100):
            h.observe(10.0)
        snap = h.snapshot()
        for _ in range(10):
            h.observe(50_000.0)
        delta = h.delta_since(snap)
        assert delta["count"] == 10
        # cumulative p99 is still dominated by the 100 fast obs;
        # the WINDOW sees only the storm
        assert cmon.snapshot_quantile(h.snapshot(), 0.5) < 100
        assert cmon.snapshot_quantile(delta, 0.5) > 10_000
        assert delta["sum"] == pytest.approx(500_000.0)

    def test_none_snapshot_is_full_view(self):
        h = cmon.Histogram()
        h.observe(7.0)
        d = h.delta_since(None)
        assert d["count"] == 1
        assert d["sum"] == pytest.approx(7.0)

    def test_boundary_mismatch_raises(self):
        h = cmon.Histogram()
        other = cmon.Histogram(per_decade=10)
        h.observe(1.0)
        with pytest.raises(ValueError, match="boundaries"):
            h.delta_since(other.snapshot())

    def test_counter_reset_falls_back_to_cumulative(self):
        old = cmon.Histogram()
        for _ in range(5):
            old.observe(100.0)
        snap = old.snapshot()
        fresh = cmon.Histogram()   # "process restarted"
        fresh.observe(200.0)
        d = fresh.delta_since(snap)
        assert d["count"] == 1     # current state IS the window
        assert d["sum"] == pytest.approx(200.0)

    def test_empty_window_quantile_sentinel(self):
        h = cmon.Histogram()
        h.observe(100.0)
        snap = h.snapshot()
        delta = h.delta_since(snap)          # nothing since
        assert delta["count"] == 0
        # sentinel, not a raise and not a fake value
        assert cmon.snapshot_quantile(delta, 0.99, empty=None) is None
        # back-compat default stays numeric (CLI renders with :.1f)
        assert cmon.snapshot_quantile(delta, 0.99) == 0.0
        assert cmon.Histogram().quantile(0.5) == 0.0
        assert cmon.Histogram().quantile(0.5, empty=None) is None

    def test_all_underflow_window_returns_sentinel_not_lo(self):
        h = cmon.Histogram()     # lo=1.0: v<=1.0 is underflow
        h.observe(500.0)
        snap = h.snapshot()
        for _ in range(3):
            h.observe(0.25)
        delta = h.delta_since(snap)
        assert delta["count"] == 3
        q = cmon.snapshot_quantile(delta, 0.99, empty=None)
        # delta windows have no min/max: an all-underflow window
        # must NOT report lo (1.0) as a fake p99
        assert q is None

    def test_live_underflow_keeps_exact_min(self):
        h = cmon.Histogram()
        h.observe(0.25)
        assert h.quantile(0.99) == pytest.approx(0.25)

    def test_single_bucket_window(self):
        h = cmon.Histogram()
        snap = h.snapshot()
        for _ in range(5):
            h.observe(100.0)
        delta = h.delta_since(snap)
        q = cmon.snapshot_quantile(delta, 0.99, empty=None)
        # inside the log bucket that holds 100 (no exact min/max in
        # a delta — bucket-edge resolution is the contract)
        assert q is not None and 50.0 < q < 200.0

    def test_overflow_window_reports_finite_lower_bound(self):
        h = cmon.Histogram(decades=3)       # top edge 1e3
        snap = h.snapshot()
        h.observe(1e9)
        delta = h.delta_since(snap)
        q = cmon.snapshot_quantile(delta, 0.99, empty=None)
        assert q is not None and math.isfinite(q)
        assert q >= 1e3      # honest lower bound: still trips alerts


# ---------------------------------------------------------------------------
# ring (b): spec grammar + rule state machines
# ---------------------------------------------------------------------------

class TestSpec:
    def test_default_pack_words(self):
        for word in ("serving", "default", "all", "1", "on", "true"):
            rules = alerts.parse_spec(word)
            assert {r.name for r in rules} == {
                "ttft_p99", "itl_p99", "shed_rate", "queue_depth",
                "kv_free_frac", "replica_unhealthy"}

    def test_explicit_rules(self):
        rules = alerts.parse_spec(
            "serve/queue_depth:threshold:gt=10:for=2;"
            "serve/hist/ttft_us:quantile:q=0.95:gt=1000:name=t95")
        assert len(rules) == 2
        assert rules[0].for_ticks == 2
        assert rules[1].q == 0.95 and rules[1].name == "t95"

    @pytest.mark.parametrize("bad", [
        "nokind",                              # missing kind
        "m:notakind:gt=1",                     # unknown kind
        "m:threshold",                         # no bound
        "m:threshold:gt=1:lt=2",               # two bounds
        "m:threshold:gt=1:bogus=3",            # unknown param
        "m:threshold:gt=oops",                 # non-numeric
        "m:quantile:q=1.5:gt=1",               # q out of range
        "m:burn_rate:gt=1:total=t",            # burn takes no op
        "m:burn_rate",                         # burn needs total
        "m:fraction:lt=0.1",                   # fraction needs of
        "m/*:quantile:gt=1",                   # glob non-threshold
        "m:threshold:gt=1:name=ba d",          # bad rule name
    ])
    def test_invalid_specs_raise(self, bad):
        with pytest.raises(ValueError):
            alerts.parse_spec(bad)

    def test_duplicate_names_rejected_at_configure(self):
        with pytest.raises(ValueError, match="duplicate"):
            alerts.configure(
                spec="a/b:threshold:gt=1:name=x;"
                     "c/d:threshold:gt=1:name=x", start=False)
        assert not alerts.armed()

    def test_configure_publishes_armed_shape(self):
        alerts.configure(spec="a/b:threshold:gt=1:name=shape",
                         start=False)
        snap = cmon.registry.snapshot()
        assert snap["alerts/armed"] == 1
        assert snap["alerts/shape/firing"] == 0
        assert snap["alerts/shape/transitions"] == 0
        alerts.disarm()
        assert cmon.registry.snapshot()["alerts/armed"] == 0


class TestStateMachine:
    def test_threshold_for_clear_hysteresis(self):
        r = alerts.AlertRule("t/depth", "threshold", gt=10,
                             name="depth", **{"for": 2, "clear": 2})
        alerts.configure(rules=[r], start=False)
        cmon.stat_set("t/depth", 5)
        alerts.evaluate_once(now=1.0)
        assert r.state == "ok"
        cmon.stat_set("t/depth", 99)
        alerts.evaluate_once(now=2.0)
        assert r.state == "pending"          # for=2: one tick isn't
        evs = alerts.evaluate_once(now=3.0)
        assert r.state == "firing"
        assert [(ru.name, ev) for ru, ev, _ in evs] == \
            [("depth", "fire")]
        cmon.stat_set("t/depth", 0)
        alerts.evaluate_once(now=4.0)
        assert r.state == "firing"           # clear=2: one clean tick
        evs = alerts.evaluate_once(now=5.0)
        assert r.state == "resolved"
        assert [(ru.name, ev) for ru, ev, _ in evs] == \
            [("depth", "resolve")]
        snap = cmon.registry.snapshot()
        assert snap["alerts/depth/firing"] == 0
        assert snap["alerts/depth/transitions"] == 2

    def test_threshold_glob_any_match(self):
        cmon.stat_set("g/replica/0/healthy", 1)
        cmon.stat_set("g/replica/1/healthy", 0)
        r = alerts.AlertRule("g/replica/*/healthy", "threshold",
                             lt=1, name="unhealthy")
        alerts.configure(rules=[r], start=False)
        alerts.evaluate_once(now=1.0)
        assert r.state == "firing" and r.value == 0

    def test_rate_and_reset_rebase(self):
        r = alerts.AlertRule("ra/errs", "rate", gt=5.0, window=10,
                             name="er", clear=1)
        alerts.configure(rules=[r], start=False)
        cmon.stat_set("ra/errs", 0)
        alerts.evaluate_once(now=0.0)
        assert r.value is None               # window still filling
        cmon.stat_set("ra/errs", 100)
        alerts.evaluate_once(now=10.0)
        assert r.state == "firing"
        assert r.value == pytest.approx(10.0)
        cmon.stat_set("ra/errs", 2)          # counter reset
        alerts.evaluate_once(now=20.0)
        assert r.value is None               # rebased, not negative
        alerts.evaluate_once(now=21.0)
        assert r.state == "resolved"

    def test_burn_rate_needs_both_windows(self):
        r = alerts.AlertRule("b/errs", "burn_rate", total="b/reqs",
                             budget=0.1, factor=2.0, window=10,
                             long=30, name="burn")
        alerts.configure(rules=[r], start=False)
        cmon.stat_set("b/errs", 0)
        cmon.stat_set("b/reqs", 0)
        alerts.evaluate_once(now=0.0)
        assert r.state == "ok"
        # 4 errors / 20 requests = 20% of traffic vs a 10% budget
        # -> burn 2.0x in BOTH windows
        cmon.stat_set("b/errs", 4)
        cmon.stat_set("b/reqs", 20)
        alerts.evaluate_once(now=10.0)
        assert r.state == "firing"
        assert r.value == pytest.approx(2.0)
        # traffic continues clean -> short window burn collapses
        cmon.stat_set("b/errs", 4)
        cmon.stat_set("b/reqs", 220)
        alerts.evaluate_once(now=25.0)
        alerts.evaluate_once(now=26.0)
        assert r.state == "resolved"

    def test_fraction(self):
        r = alerts.AlertRule("f/free", "fraction", of="f/used",
                             lt=0.2, name="freefrac")
        alerts.configure(rules=[r], start=False)
        cmon.stat_set("f/free", 50)
        cmon.stat_set("f/used", 50)
        alerts.evaluate_once(now=1.0)
        assert r.state == "ok" and r.value == pytest.approx(0.5)
        cmon.stat_set("f/free", 5)
        cmon.stat_set("f/used", 95)
        alerts.evaluate_once(now=2.0)
        assert r.state == "firing"

    def test_absence_fires_until_series_appears(self):
        r = alerts.AlertRule("ab/never", "absence", name="gone",
                             clear=1)
        alerts.configure(rules=[r], start=False)
        alerts.evaluate_once(now=1.0)
        assert r.state == "firing"
        cmon.stat_set("ab/never", 1)
        alerts.evaluate_once(now=2.0)
        assert r.state == "resolved"

    def test_absence_sees_histograms(self):
        cmon.hist_observe("ab/hist_series", 1.0)
        r = alerts.AlertRule("ab/hist_series", "absence", name="ha")
        alerts.configure(rules=[r], start=False)
        alerts.evaluate_once(now=1.0)
        assert r.state == "ok"

    def test_quantile_windowed_storm_then_recovery(self):
        h = cmon.hist_get("qa/lat_us")
        for _ in range(200):
            h.observe(50.0)
        r = alerts.AlertRule("qa/lat_us", "quantile", q=0.9,
                             gt=10_000.0, name="lat", clear=1)
        alerts.configure(rules=[r], start=False)
        alerts.evaluate_once(now=1.0)       # baseline the window
        assert r.state == "ok"
        for _ in range(20):
            h.observe(90_000.0)
        alerts.evaluate_once(now=2.0)
        # cumulative p90 is still ~50 (200 fast vs 20 slow) — only
        # the windowed delta can see the storm
        assert r.state == "firing"
        assert r.value == pytest.approx(90_000.0, rel=0.2)
        for _ in range(50):
            h.observe(60.0)
        alerts.evaluate_once(now=3.0)
        assert r.state == "resolved"

    def test_listener_fanout_and_errors_counted(self):
        got = []
        boom = lambda *a: (_ for _ in ()).throw(RuntimeError("x"))
        alerts.add_listener(boom)
        alerts.add_listener(lambda ru, ev, v: got.append((ru.name,
                                                          ev)))
        try:
            r = alerts.AlertRule("li/x", "threshold", gt=1,
                                 name="li")
            alerts.configure(rules=[r], start=False)
            cmon.stat_set("li/x", 5)
            alerts.evaluate_once(now=1.0)
            assert got == [("li", "fire")]
            assert cmon.registry.snapshot()[
                "alerts/listener_errors"] >= 1
        finally:
            alerts.remove_listener(boom)
            alerts._listeners.clear()

    def test_evaluator_thread_lifecycle(self):
        alerts.configure(spec="th/x:threshold:gt=1:name=th",
                         start=True, interval_s=0.05)
        names = [t.name for t in threading.enumerate()]
        assert "paddle-alert-evaluator" in names
        alerts.disarm()
        names = [t.name for t in threading.enumerate()]
        assert "paddle-alert-evaluator" not in names


# ---------------------------------------------------------------------------
# satellite 1: flight-ring gauge staleness
# ---------------------------------------------------------------------------

class TestFlightGaugeSync:
    def test_just_recorded_gauge_visible_to_next_tick(self):
        flight.record("alerts_test_seed")
        true_before = flight.recorder.stats()["events"]
        r = alerts.AlertRule("flight/events", "threshold",
                             ge=true_before + 1, name="flfresh")
        alerts.configure(rules=[r], start=False)
        flight.record("alerts_test_marker")
        marker_seq = flight.recorder.stats()["events"]
        alerts.evaluate_once(now=1.0)
        # the ring amortizes gauge pushes to every 256th record —
        # the tick must force the sync, see the marker, and fire
        # (the alert_fire event it then records bumps the live seq
        # past what the tick saw, so compare against marker time)
        assert r.value >= marker_seq
        assert r.value >= true_before + 1
        assert r.state == "firing"
        assert cmon.registry.snapshot()["flight/events"] >= \
            marker_seq


# ---------------------------------------------------------------------------
# /alertz + CLI
# ---------------------------------------------------------------------------

class TestAlertz:
    def test_route_registered_and_gated(self):
        routes = {p: armed for p, _, armed in mserver.ROUTES}
        assert routes["/alertz"] == "PADDLE_ALERTS"

    def test_alertz_payload(self):
        alerts.configure(spec="az/x:threshold:gt=1:name=az",
                         start=False)
        cmon.stat_set("az/x", 5)
        srv = mserver.DebugServer(port=0, host="127.0.0.1").start()
        try:
            alerts.evaluate_once(now=1.0)
            with urllib.request.urlopen(srv.url + "/alertz",
                                        timeout=5) as resp:
                doc = json.loads(resp.read())
        finally:
            srv.shutdown()
        assert doc["armed"] is True
        assert doc["rank"] == 0
        (rule,) = doc["rules"]
        assert rule["name"] == "az" and rule["state"] == "firing"
        assert rule["value"] == 5

    def test_index_lists_alertz(self):
        srv = mserver.DebugServer(port=0, host="127.0.0.1").start()
        try:
            with urllib.request.urlopen(srv.url + "/",
                                        timeout=5) as resp:
                doc = json.loads(resp.read())
        finally:
            srv.shutdown()
        assert "/alertz" in doc["routes"]


class TestCLI:
    def test_lists_kinds_and_default_pack(self, capsys):
        rc = cli_main(["alerts"])
        out = capsys.readouterr().out
        assert rc == 0
        for kind in alerts.KINDS:
            assert kind in out
        assert "ttft_p99" in out and "replica_unhealthy" in out

    def test_valid_spec_exits_0(self, capsys):
        rc = cli_main(["alerts", "serve/shed:rate:gt=0.5"])
        assert rc == 0
        assert "spec OK — 1 rule(s)" in capsys.readouterr().out

    def test_invalid_spec_exits_2(self, capsys):
        rc = cli_main(["alerts", "serve/shed:bogus"])
        captured = capsys.readouterr()
        assert rc == 2
        assert "error: invalid alert spec" in captured.err

    def test_json_view(self, capsys):
        rc = cli_main(["alerts", "serving", "--json"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert set(doc["kinds"]) == set(alerts.KINDS)
        assert len(doc["default_pack"]) == 6
        assert len(doc["rules"]) == 6
        assert doc["live"]["armed"] is False


# ---------------------------------------------------------------------------
# satellite 3: fleet/scrape alert rollup
# ---------------------------------------------------------------------------

def _alert_spool(rank, firing=None, transitions=0):
    stats = {"step/count": 5, "step/total_time_us": 5000.0}
    if firing is not None:
        stats.update({"alerts/armed": 1,
                      "alerts/ttft_p99/firing": firing,
                      "alerts/ttft_p99/transitions": transitions})
    return {"ts": 1700000000.0 + rank, "rank": rank,
            "stats": stats, "hists": {}}


class TestFleetRollup:
    def _spools(self, tmp_path):
        spools = [_alert_spool(0, firing=1, transitions=1),
                  _alert_spool(1, firing=0, transitions=2),
                  _alert_spool(2)]           # never armed
        paths = []
        for s in spools:
            p = tmp_path / f"rank{s['rank']}.json"
            p.write_text(json.dumps(s))
            paths.append(str(p))
        return paths

    def test_fleet_text_rollup(self, tmp_path, capsys):
        rc = cli_main(["fleet"] + self._spools(tmp_path))
        out = capsys.readouterr().out
        assert rc == 0
        assert "alerts (FIRING; armed on ranks [0, 1])" in out
        assert "ttft_p99  firing=r0  resolved=r1" in out

    def test_fleet_json_rollup(self, tmp_path, capsys):
        rc = cli_main(["fleet", "--json"] + self._spools(tmp_path))
        assert rc == 0
        view = json.loads(capsys.readouterr().out)
        roll = view["alerts"]
        assert roll["any_firing"] is True
        assert roll["armed_ranks"] == [0, 1]
        assert roll["rules"]["ttft_p99"]["firing"] == [0]
        assert roll["rules"]["ttft_p99"]["resolved"] == [1]
        assert roll["rules"]["ttft_p99"]["ok"] == []
        # per-rank alert gauges never sum across ranks
        assert "alerts/ttft_p99/firing" in view["gauges"]

    def test_unarmed_fleet_has_quiet_rollup(self, tmp_path, capsys):
        p = tmp_path / "rank0.json"
        p.write_text(json.dumps(_alert_spool(0)))
        rc = cli_main(["fleet", str(p)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "alerts (" not in out         # section only when armed

    def test_scrape_rollup_partial_fleet_exit_1(self, capsys):
        # one live rank firing, one live rank never armed, one dead
        # target: rollup lands AND the exit-1 contract is preserved
        snaps = [_alert_spool(0, firing=1, transitions=1),
                 _alert_spool(1)]
        servers = [mserver.DebugServer(
            port=0, host="127.0.0.1",
            snapshot_fn=(lambda s=s: s)).start() for s in snaps]
        dead = socket.socket()
        dead.bind(("127.0.0.1", 0))
        dead_port = dead.getsockname()[1]
        dead.close()
        try:
            rc = cli_main(
                ["scrape", "--no-flight", "--timeout", "2",
                 f"127.0.0.1:{servers[0].port}",
                 f"127.0.0.1:{servers[1].port}",
                 f"127.0.0.1:{dead_port}"])
        finally:
            for s in servers:
                s.shutdown()
        captured = capsys.readouterr()
        assert rc == 1
        assert "alerts (FIRING; armed on ranks [0])" in captured.out
        assert "ttft_p99  firing=r0" in captured.out
        assert str(dead_port) in captured.err

    def test_scrape_prefers_alertz_payload(self, capsys):
        # the LOCAL engine is armed but quiet: /alertz (global state)
        # overrides the spool-stats inference for every scraped rank
        alerts.configure(
            spec="sc/x:threshold:gt=1:name=scq", start=False)
        snap = _alert_spool(0, firing=1, transitions=1)
        srv = mserver.DebugServer(
            port=0, host="127.0.0.1",
            snapshot_fn=(lambda: snap)).start()
        try:
            rc = cli_main(["scrape", "--no-flight", "--json",
                           "--timeout", "2",
                           f"127.0.0.1:{srv.port}"])
        finally:
            srv.shutdown()
        assert rc == 0
        view = json.loads(capsys.readouterr().out)
        roll = view["alerts"]
        assert roll["armed_ranks"] == [0]
        # exact rule state from /alertz (ok), not the stats-inferred
        # "firing" the synthetic spool would suggest
        assert roll["rules"]["scq"]["ok"] == [0]
        assert roll["any_firing"] is False


# ---------------------------------------------------------------------------
# ring (d): the closed observability->capacity loop (acceptance gate)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestClosedLoop:
    def test_latency_storm_scales_up_then_resolves(
            self, model, prompts, want):
        base = cmon.registry.snapshot()
        b_spawns = base.get("serve/autoscale/spawns", 0)
        b_drains = base.get("serve/autoscale/drains", 0)
        router = Router(model, replicas=1, max_batch=4,
                        block_size=8, num_blocks=32,
                        heartbeat_timeout_s=60.0)
        rule = alerts.AlertRule(
            "serve/hist/ttft_us", "quantile", q=0.5, gt=50_000.0,
            name="ttft_p99", clear=1)
        scaler = None
        try:
            # warm the router FIRST so compile-time TTFTs can't
            # masquerade as the storm
            outs_cold = router.generate(prompts, sampling=sp())
            assert outs_cold == want
            alerts.configure(rules=[rule], start=False)
            # the first tick's window is the FULL cumulative hist
            # (compile-time TTFTs from earlier fixtures included) —
            # absorb it, then prove a clean window is quiet, and
            # only then wire the autoscaler in
            alerts.evaluate_once()
            alerts.evaluate_once()
            outs_quiet = router.generate(prompts, sampling=sp())
            assert outs_quiet == want
            alerts.evaluate_once()
            assert rule.state in ("ok", "resolved")
            scaler = Autoscaler(router, rule="ttft_p99",
                                min_replicas=1, max_replicas=2,
                                cooldown_s=0.0).attach()
            assert len(router._live()) == 1
            # chaos latency storm: +100ms at every admission — the
            # arrival->first-token span (TTFT is prefill-bound; a
            # decode delay would only show up in ITL) — so every
            # TTFT in this window blows the 50ms target
            with chaos.inject("serve_admit", "delay", ms=100):
                outs_storm = router.generate(prompts, sampling=sp())
            assert outs_storm == want        # slow, never wrong
            evs = alerts.evaluate_once()
            assert [(r.name, ev) for r, ev, _ in evs] == \
                [("ttft_p99", "fire")]
            assert rule.state == "firing"
            assert rule.value > 50_000.0     # the storm, not noise
            # the autoscaler spawned replica 1 off the same recipe
            assert len(router._live()) == 2
            # recovery wave on the scaled fleet absorbs the new
            # replica's first-dispatch compiles into a window we
            # never assert on...
            outs_warm = router.generate(prompts, sampling=sp())
            assert outs_warm == want
            alerts.evaluate_once()
            # ...then a warm wave proves the SLO recovered
            if rule.state == "firing":
                outs_clean = router.generate(prompts, sampling=sp())
                assert outs_clean == want
                alerts.evaluate_once()
            assert rule.state == "resolved"
            # resolve drained back to min_replicas, token-exactly
            assert len(router._live()) == 1
            snap = cmon.registry.snapshot()
            assert snap["serve/autoscale/spawns"] - b_spawns == 1
            assert snap["serve/autoscale/drains"] - b_drains == 1
            assert snap["serve/autoscale/replicas"] == 1
            assert snap["alerts/ttft_p99/transitions"] >= 2
            assert_no_leaks(router)
        finally:
            if scaler is not None:
                scaler.detach()
            alerts.disarm()
            router.shutdown()

    def test_retire_replica_replays_in_flight(self, model, prompts,
                                              want):
        """Planned scale-down mid-flood: the retired replica's live
        requests replay token-identically on the survivor."""
        router = Router(model, replicas=2, max_batch=4,
                        block_size=8, num_blocks=32,
                        heartbeat_timeout_s=60.0)
        try:
            ids = [router.submit(p, sampling=sp()) for p in prompts]
            retired = router.retire_replica()
            assert retired == 1
            assert len(router._live()) == 1
            router.wait(ids, timeout_s=120.0)
            outs = [router._records[i].req.output_ids for i in ids]
            assert outs == want
            for i in ids:
                router.release(i)
            assert_no_leaks(router)
        finally:
            router.shutdown()

    def test_retire_refuses_last_replica(self, model):
        router = Router(model, replicas=1, max_batch=2,
                        block_size=8, num_blocks=32,
                        heartbeat_timeout_s=60.0)
        try:
            with pytest.raises(RuntimeError, match="last healthy"):
                router.retire_replica()
        finally:
            router.shutdown()

    def test_autoscaler_clamps_and_cooldown(self, model):
        router = Router(model, replicas=1, max_batch=2,
                        block_size=8, num_blocks=32,
                        heartbeat_timeout_s=60.0)
        scaler = Autoscaler(router, min_replicas=1, max_replicas=1,
                            cooldown_s=3600.0)
        try:
            # at max already -> suppressed, no spawn
            assert scaler.scale_up() is None
            assert len(router._live()) == 1
            scaler.max_replicas = 2
            assert scaler.scale_up() is not None
            # inside the cooldown -> suppressed
            assert scaler.scale_down() is None
            assert len(router._live()) == 2
        finally:
            scaler.detach()
            router.shutdown()


# ---------------------------------------------------------------------------
# disarmed provenance (subprocess: a fresh registry proves absence)
# ---------------------------------------------------------------------------

class TestDisarmedContract:
    def test_disarmed_is_thread_and_counter_free(self):
        code = """
import os, threading
for k in ("PADDLE_ALERTS", "PADDLE_SERVE_AUTOSCALE"):
    os.environ.pop(k, None)
import paddle_tpu.inference.serving as s
from paddle_tpu.core import monitor as cmon
from paddle_tpu.monitor import alerts
assert not alerts.armed()
assert alerts.describe()["rules"] == []
names = [t.name for t in threading.enumerate()]
assert "paddle-alert-evaluator" not in names, names
leaked = {k: v for k, v in cmon.registry.snapshot().items()
          if k.startswith(("alerts/", "serve/autoscale/"))}
assert not leaked, leaked
print("CLEAN")
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PADDLE_ALERTS", None)
        env.pop("PADDLE_SERVE_AUTOSCALE", None)
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True,
                             timeout=120, cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "CLEAN" in out.stdout

    def test_env_autostart_and_bad_spec_loud(self):
        code = """
from paddle_tpu.core import monitor as cmon
from paddle_tpu.monitor import alerts
assert alerts.armed(), "PADDLE_ALERTS did not autostart"
assert [r.name for r in alerts.rules()] == ["auto"]
alerts.disarm()
print("ARMED-OK")
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PADDLE_ALERTS="a/b:threshold:gt=1:name=auto",
                   PADDLE_ALERT_INTERVAL_S="60")
        out = subprocess.run([sys.executable, "-c", code], env=env,
                             capture_output=True, text=True,
                             timeout=120, cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "ARMED-OK" in out.stdout

        code_bad = """
from paddle_tpu.core import monitor as cmon
from paddle_tpu.monitor import alerts
assert not alerts.armed()
assert cmon.registry.snapshot()["alerts/spec_errors"] == 1
print("LOUD-OK")
"""
        env["PADDLE_ALERTS"] = "totally:bogus:spec"
        out = subprocess.run([sys.executable, "-c", code_bad],
                             env=env, capture_output=True,
                             text=True, timeout=120, cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "LOUD-OK" in out.stdout

    def test_dump_bundle_carries_alerts_section(self, tmp_path):
        alerts.configure(spec="db/x:threshold:gt=1:name=db",
                         start=False)
        path = flight.write_dump("alerts_test",
                                 path=str(tmp_path / "dump.json"))
        with open(path) as f:
            bundle = json.load(f)
        assert bundle["alerts"]["armed"] is True
        assert bundle["alerts"]["rules"][0]["name"] == "db"
