"""Static-graph quantization passes (reference:
fluid/contrib/slim/quantization/quantization_pass.py QAT transform +
freeze, post_training_quantization.py PTQ): Program-rewrite fake-quant
with an int8 MNIST round-trip."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.static as static
from paddle_tpu.quantization.static_quant import (
    QuantizationFreezePass, QuantizationTransformPass,
    calibrate_program, quant_post_static)


@pytest.fixture()
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _mnist_program(seed=0):
    paddle.seed(seed)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        img = static.data("img", [None, 1, 28, 28], "float32")
        label = static.data("label", [None, 1], "int64")
        from paddle_tpu.vision.models import LeNet

        net = LeNet()
        logits = net(img)
        loss = paddle.nn.functional.cross_entropy(
            logits, paddle.squeeze(label, -1))
    return main, startup, img, label, logits, loss


def _batch(n=32, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.rand(n, 1, 28, 28).astype(np.float32)
    ys = rng.randint(0, 10, (n, 1)).astype(np.int64)
    return xs, ys


def test_qat_transform_rewrites_and_trains(static_mode):
    """QAT: the transform pass rewrites conv/linear kernels with
    fake-quant BEFORE minimize; the rewritten Program still trains
    (straight-through estimator keeps gradients flowing)."""
    paddle.seed(0)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        img = static.data("img", [None, 1, 28, 28], "float32")
        label = static.data("label", [None, 1], "int64")
        from paddle_tpu.vision.models import LeNet

        net = LeNet()
        logits = net(img)
        loss = paddle.nn.functional.cross_entropy(
            logits, paddle.squeeze(label, -1))
        qat = QuantizationTransformPass()
        qat.apply(main)
        assert qat.rewritten >= 3  # LeNet: 2 convs + 3 linears
        opt = paddle.optimizer.Adam(learning_rate=1e-3)
        opt.minimize(loss)
    exe = static.Executor()
    exe.run(startup)
    xs, ys = _batch(32)
    losses = []
    for _ in range(6):
        l, = exe.run(main, feed={"img": xs, "label": ys},
                     fetch_list=[loss])
        losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_calibration_collects_activation_scales(static_mode):
    main, _, img, label, logits, loss = _mnist_program()
    xs, ys = _batch(16)
    scales = calibrate_program(main, [{"img": xs}])
    assert len(scales) >= 3
    assert all(s > 0 for s in scales.values())
    # two batches: scales take the running max
    xs2 = xs * 3.0
    scales2 = calibrate_program(main, [{"img": xs}, {"img": xs2}])
    assert all(scales2[k] >= scales[k] for k in scales)


def test_ptq_int8_mnist_roundtrip(static_mode, tmp_path):
    """VERDICT r4 #8 'done' criterion: static MNIST PTQ — calibrate,
    freeze to STORED int8 weights, outputs stay close to fp32, and
    the quantized Program round-trips save/load_inference_model."""
    import jax.numpy as jnp

    main, _, img, label, logits, loss = _mnist_program()
    xs, ys = _batch(32)
    exe = static.Executor()
    ref_logits, = exe.run(main, feed={"img": xs, "label": ys},
                          fetch_list=[logits])

    _, freeze = quant_post_static(main, [{"img": xs}],
                                  fetch_list=[logits])
    assert freeze.frozen >= 3
    # weights are STORED int8 now
    int8_leaves = [p for p in main.all_parameters()
                   if p._value.dtype == jnp.int8]
    assert len(int8_leaves) >= 3
    q_logits, = exe.run(main, feed={"img": xs, "label": ys},
                        fetch_list=[logits])
    # int8 is lossy but close; ranking agreement on most samples
    err = np.abs(q_logits - ref_logits).mean() / (
        np.abs(ref_logits).mean() + 1e-6)
    assert err < 0.1, err
    agree = (q_logits.argmax(1) == ref_logits.argmax(1)).mean()
    assert agree > 0.9, agree

    # round-trip through the inference-model serializer
    prefix = str(tmp_path / "q")
    static.save_inference_model(prefix, [img], [logits])
    paddle.disable_static()
    try:
        prog, feeds, fetches = static.load_inference_model(prefix)
        res = exe.run(prog, feed={"img": xs}, fetch_list=fetches)
        np.testing.assert_allclose(res[0], q_logits, rtol=1e-5,
                                   atol=1e-5)
    finally:
        paddle.enable_static()


def test_freeze_skips_activation_activation_matmul(static_mode):
    """A matmul of two computed intermediates has no weight to store —
    the freeze pass must skip it, not clobber a Variable."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        a = static.data("a", [4, 8], "float32")
        h = a * 2.0
        out = paddle.matmul(h, paddle.transpose(h, [1, 0]))
    p = QuantizationFreezePass({})
    p.apply(main)
    assert p.frozen == 0
    exe = static.Executor()
    av = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    r, = exe.run(main, feed={"a": av}, fetch_list=[out])
    np.testing.assert_allclose(r, (av * 2) @ (av * 2).T, rtol=1e-5)


def _eager_vs_executor(main, exe, feed, fetch):
    """Run the Program through BOTH regimes: Executor (replay inside
    jax.jit — the to_static path) and _eager_replay (the recorded
    kernels executed eagerly). A kernel rewrite must read identically
    through both — XLA fusing the quant arithmetic into the
    surrounding matmul cannot change the numbers."""
    from paddle_tpu.quantization.static_quant import _eager_replay

    compiled, = exe.run(main, feed=feed, fetch_list=[fetch])
    env = _eager_replay(main, feed)
    eager = np.asarray(env[id(fetch)])
    return compiled, eager


def test_qat_program_eager_vs_to_static_parity(static_mode):
    """ISSUE-14 satellite: the QAT-rewritten Program produces the
    same numbers eagerly and compiled (and really changed them vs
    the unrewritten program)."""
    main, _, img, label, logits, loss = _mnist_program()
    exe = static.Executor()
    xs, ys = _batch(16)
    feed = {"img": xs, "label": ys}
    ref, _ = _eager_vs_executor(main, exe, feed, logits)
    qat = QuantizationTransformPass()
    qat.apply(main)
    assert qat.rewritten >= 3
    compiled, eager = _eager_vs_executor(main, exe, feed, logits)
    np.testing.assert_allclose(compiled, eager, rtol=1e-4,
                               atol=1e-4)
    assert not np.array_equal(compiled, ref)  # rewrite took effect


def test_frozen_int8_program_eager_vs_to_static_parity(static_mode):
    """ISSUE-14 satellite: the frozen weight-only-int8 Program (plus
    calibrated static activation scales) reads the same through the
    eager replay and the jit-compiled Executor. The static path
    re-quantizes ACTIVATIONS with round(); XLA's float reassociation
    can flip a value sitting exactly on a rounding boundary into the
    neighboring bin, so agreement is gated at quantization-step
    scale (plus exact class agreement) — what a real dequant bug
    (e.g. a double-applied scale, ~127x off) can never satisfy."""
    import jax.numpy as jnp

    main, _, img, label, logits, loss = _mnist_program()
    exe = static.Executor()
    xs, ys = _batch(16)
    feed = {"img": xs, "label": ys}
    _, freeze = quant_post_static(main, [feed], fetch_list=[logits])
    assert freeze.frozen >= 3
    assert any(p._value.dtype == jnp.int8
               for p in main.all_parameters())
    compiled, eager = _eager_vs_executor(main, exe, feed, logits)
    np.testing.assert_allclose(
        compiled, eager, atol=0.05 * np.abs(eager).max())
    assert (compiled.argmax(1) == eager.argmax(1)).mean() >= 0.95


def test_freeze_shared_weight_quantized_once(static_mode):
    """Review r4: a weight leaf shared by two quantizable ops (tied
    weights) must quantize ONCE with one scale — re-deriving from the
    already-int8 leaf would dequantize ~127x too large."""
    import jax.numpy as jnp

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [4, 8], "float32")
        lin = nn.Linear(8, 8)
        h = lin(x)
        out = paddle.nn.functional.linear(h, lin.weight)  # tied reuse
    exe = static.Executor()
    xs = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    ref, = exe.run(main, feed={"x": xs}, fetch_list=[out])
    p = QuantizationFreezePass({})
    p.apply(main)
    assert p.frozen == 2  # both ops rewritten...
    int8_leaves = [q for q in main.all_parameters()
                   if q._value.dtype == jnp.int8]
    assert len(int8_leaves) == 1  # ...but ONE leaf quantized once
    got, = exe.run(main, feed={"x": xs}, fetch_list=[out])
    err = np.abs(got - ref).mean() / (np.abs(ref).mean() + 1e-6)
    assert err < 0.1, err
