"""Subprocess PS shard for the graph-table test: hosts one PSServer
on the given port until stdin closes."""
import sys

import jax

jax.config.update("jax_platforms", "cpu")

from paddle_tpu.distributed.ps import PSServer  # noqa: E402


def main():
    port = int(sys.argv[1])
    sid = int(sys.argv[2])
    srv = PSServer(port=port, server_id=sid)
    print(f"READY {srv.endpoint}", flush=True)
    sys.stdin.read()  # parent closes stdin to stop us
    srv.stop()


if __name__ == "__main__":
    main()
