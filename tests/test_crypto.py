"""Model crypto tests (r4 verdict missing #6). Reference:
paddle/fluid/pybind/crypto.cc + framework/io/crypto/aes_cipher_test.cc."""
import numpy as np
import pytest

from paddle_tpu.utils.crypto import AESCipher, CipherFactory, CipherUtils


def test_ctr_roundtrip_bytes_and_file(tmp_path):
    key = CipherUtils.gen_key(256)
    c = AESCipher("AES_CTR_NoPadding")
    msg = b"model bytes \x00\x01\x02" * 100
    ct = c.encrypt(msg, key)
    assert ct != msg and len(ct) == len(msg) + 16  # IV || body
    assert c.decrypt(ct, key) == msg
    # fresh IV per encryption
    assert c.encrypt(msg, key) != ct
    p = tmp_path / "enc.bin"
    c.encrypt_to_file(msg, key, str(p))
    assert c.decrypt_from_file(key, str(p)) == msg


def test_gcm_tamper_detection():
    key = CipherUtils.gen_key(128)
    c = AESCipher("AES_GCM_NoPadding")
    msg = b"authenticated model payload"
    ct = bytearray(c.encrypt(msg, key))
    assert c.decrypt(bytes(ct), key) == msg
    ct[20] ^= 0xFF  # flip a body byte
    with pytest.raises(Exception):
        c.decrypt(bytes(ct), key)


def test_factory_config_and_key_file(tmp_path):
    cfg = tmp_path / "cipher.conf"
    cfg.write_text("# model cipher config\n"
                   "cipher_name AES_GCM_NoPadding\n"
                   "iv_size 128\n"
                   "tag_size 128\n")
    c = CipherFactory.create_cipher(str(cfg))
    assert isinstance(c, AESCipher) and c._name == "AES_GCM_NoPadding"
    key = CipherUtils.gen_key_to_file(256, str(tmp_path / "k.bin"))
    assert CipherUtils.read_key_from_file(str(tmp_path / "k.bin")) == key
    # default factory: CTR (reference cipher.cc default)
    assert CipherFactory.create_cipher()._name == "AES_CTR_NoPadding"


def test_encrypted_model_artifact_roundtrip(tmp_path):
    """Encrypt a jit.save artifact, decrypt, reload, same outputs —
    the end-to-end 'ship encrypted inference model' flow."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import InputSpec, load, save

    paddle.seed(0)
    net = nn.Linear(4, 2)
    x = paddle.to_tensor(np.ones((1, 4), np.float32))
    want = net(x).numpy()
    save(net, str(tmp_path / "m"),
         input_spec=[InputSpec(shape=[1, 4], dtype="float32")])

    key = CipherUtils.gen_key(256)
    c = AESCipher("AES_GCM_NoPadding")
    raw = open(tmp_path / "m.pdiparams", "rb").read()
    c.encrypt_to_file(raw, key, str(tmp_path / "m.pdiparams.enc"))
    (tmp_path / "m.pdiparams").unlink()

    # consumer side: decrypt params, restore, load
    dec = c.decrypt_from_file(key, str(tmp_path / "m.pdiparams.enc"))
    open(tmp_path / "m.pdiparams", "wb").write(dec)
    m2 = load(str(tmp_path / "m"))
    out = m2(x)
    if isinstance(out, (list, tuple)):
        out = out[0]
    np.testing.assert_allclose(np.squeeze(out.numpy()),
                               np.squeeze(want), rtol=1e-6)
