"""Parameter server: tables, RPC, sharding, async communicator,
distributed embedding training (reference:
ps/service/brpc_ps_{client,server}.cc, ps/table/, the_one_ps.py:606)."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (AsyncCommunicator,
                                       DistributedEmbedding, PSClient,
                                       PSServer)


@pytest.fixture()
def cluster():
    servers = [PSServer(server_id=i) for i in range(2)]
    client = PSClient([s.endpoint for s in servers])
    yield servers, client
    client.close()
    for s in servers:
        s.stop()


def test_dense_table_pull_push(cluster):
    _, c = cluster
    c.create_dense_table("w", (4, 3), initializer=np.ones((4, 3)))
    w0 = c.pull_dense("w")
    np.testing.assert_array_equal(w0, 1.0)
    c.push_dense("w", np.full((4, 3), 0.5), lr=1.0)
    np.testing.assert_allclose(c.pull_dense("w"), 0.5)


def test_sparse_table_shard_pull_push(cluster):
    servers, c = cluster
    c.create_sparse_table("emb", emb_dim=4, initializer="zeros")
    ids = np.array([0, 1, 2, 3, 10, 11], np.int64)
    rows = c.pull_sparse("emb", ids)
    assert rows.shape == (6, 4)
    np.testing.assert_array_equal(rows, 0.0)
    # rows landed on both shards (even ids -> server 0, odd -> 1)
    assert servers[0]._sparse["emb"].size() == 3
    assert servers[1]._sparse["emb"].size() == 3
    grads = np.ones((6, 4), np.float32)
    c.push_sparse("emb", ids, grads, lr=0.5)
    np.testing.assert_allclose(c.pull_sparse("emb", ids), -0.5)


def test_sparse_rows_lazily_initialized_deterministic(cluster):
    _, c = cluster
    c.create_sparse_table("e2", emb_dim=8)
    a = c.pull_sparse("e2", [100])
    b = c.pull_sparse("e2", [100])
    np.testing.assert_array_equal(a, b)  # same row on re-pull
    assert np.abs(a).max() > 0  # uniform init, not zeros


def test_save_load_roundtrip(cluster, tmp_path):
    servers, c = cluster
    c.create_sparse_table("e3", emb_dim=2, initializer="zeros")
    c.push_sparse("e3", [1, 2], np.ones((2, 2)), lr=1.0)
    c.save(str(tmp_path / "ckpt"))
    c.push_sparse("e3", [1, 2], np.ones((2, 2)), lr=1.0)  # diverge
    c.load(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(c.pull_sparse("e3", [1, 2]), -1.0)


def test_barrier_two_workers(cluster):
    _, c = cluster
    c2 = PSClient(c._endpoints)
    errs = []

    def other():
        try:
            c2.barrier("sync1", 2, timeout=5)
        except Exception as e:
            errs.append(e)

    t = threading.Thread(target=other)
    t.start()
    c.barrier("sync1", 2, timeout=5)
    t.join(timeout=5)
    assert not errs
    c2.close()


def test_barrier_key_reusable_across_epochs(cluster):
    """The same barrier key must synchronize again next epoch
    (round-2 review: stale counts made later barriers no-ops)."""
    _, c = cluster
    c2 = PSClient(c._endpoints)
    errs = []

    def other(n_epochs):
        try:
            for _ in range(n_epochs):
                c2.barrier("ep", 2, timeout=5)
        except Exception as e:
            errs.append(e)

    t = threading.Thread(target=other, args=(2,))
    t.start()
    c.barrier("ep", 2, timeout=5)
    c.barrier("ep", 2, timeout=5)
    t.join(timeout=10)
    assert not errs
    # epoch 3 with only ONE participant must time out (no stale count)
    with pytest.raises(TimeoutError):
        c.barrier("ep", 2, timeout=0.5)
    c2.close()


def test_save_load_preserves_table_config(cluster, tmp_path):
    """Restore into a fresh server must keep optimizer rule + lr."""
    servers, c = cluster
    c.create_sparse_table("cfg_t", emb_dim=2, optimizer="adagrad",
                          lr=0.01, initializer="zeros")
    c.push_sparse("cfg_t", [4], np.ones((1, 2)))
    c.save(str(tmp_path / "cfg"))
    # wipe server-side tables, then load
    for s in servers:
        s._sparse.clear()
    c.load(str(tmp_path / "cfg"))
    tbl = servers[0]._sparse["cfg_t"]
    assert tbl.optimizer == "adagrad" and tbl.lr == 0.01


def test_distributed_embedding_bounds_check(cluster):
    _, c = cluster
    emb = DistributedEmbedding(c, "bounded", num_embeddings=10,
                               emb_dim=2)
    with pytest.raises(IndexError, match="out of range"):
        emb(np.array([3, 99], np.int64))


def test_async_communicator_flushes(cluster):
    _, c = cluster
    c.create_sparse_table("e4", emb_dim=2, initializer="zeros")
    comm = AsyncCommunicator(c, flush_interval=0.01)
    comm.push_sparse_async("e4", [7], np.ones((1, 2)), lr=1.0)
    comm.stop()  # stop() flushes
    np.testing.assert_allclose(c.pull_sparse("e4", [7]), -1.0)


def test_distributed_embedding_trains(cluster):
    """CTR-style run: PS-hosted embedding + local dense head; loss
    decreases and sparse rows update through the backward hook."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim

    _, c = cluster
    paddle.seed(0)
    emb = DistributedEmbedding(c, "ctr_emb", num_embeddings=1000,
                               emb_dim=8, lr=0.5)
    head = nn.Linear(8, 1)
    opt = optim.SGD(learning_rate=0.1, parameters=head.parameters())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1000, (16,)).astype(np.int64)
    y = (ids % 2).astype(np.float32).reshape(16, 1)

    losses = []
    for _ in range(30):
        e = emb(paddle.to_tensor(ids))
        out = head(e)
        loss = ((out - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0] * 0.8
    assert c.sparse_size("ctr_emb") == len(np.unique(ids))


# -- r4: SSD spill table, geo-async, InMemoryDataset ingest ------------------

def _train_embedding(client, table_name, steps=25, **table_kw):
    """Seeded embedding+head run; returns the loss curve."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim

    paddle.seed(0)
    emb = DistributedEmbedding(client, table_name, num_embeddings=500,
                               emb_dim=8, lr=0.5, **table_kw)
    head = nn.Linear(8, 1)
    opt = optim.SGD(learning_rate=0.1, parameters=head.parameters())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 500, (64,)).astype(np.int64)
    y = (ids % 2).astype(np.float32).reshape(-1, 1)
    losses = []
    for _ in range(steps):
        e = emb(paddle.to_tensor(ids))
        out = head(e)
        loss = ((out - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    return losses


def test_ssd_table_spills_with_loss_parity(cluster):
    """VERDICT r4 #2 'done' criterion: a table whose row count exceeds
    the in-memory budget spills to disk AND the training curve is
    IDENTICAL to the in-memory table's (the spill is transparent)."""
    _, c = cluster
    mem_losses = _train_embedding(c, "mem_emb")
    ssd_losses = _train_embedding(c, "ssd_emb", table_class="ssd",
                                  mem_budget_rows=10)
    np.testing.assert_allclose(ssd_losses, mem_losses, rtol=1e-6)
    stats = c.sparse_stats("ssd_emb")
    assert stats["disk_rows"] > 0, stats       # it DID spill
    assert stats["mem_rows"] <= 2 * 10, stats  # per-shard budget held
    assert stats["spills"] > 0 and stats["faults"] > 0, stats
    # total rows = union of touched ids, none lost to the spill
    assert c.sparse_size("ssd_emb") == c.sparse_size("mem_emb")


def test_ssd_table_save_load_includes_disk_rows(cluster, tmp_path):
    from paddle_tpu.distributed.ps import SSDSparseTable

    _, c = cluster
    c.create_sparse_table("ssd_sv", 4, table_class="ssd",
                          mem_budget_rows=3, initializer="zeros")
    ids = np.arange(20)
    c.push_sparse("ssd_sv", ids, np.ones((20, 4), np.float32), lr=1.0)
    before = c.pull_sparse("ssd_sv", ids)
    path = str(tmp_path / "ssd_ckpt")
    c.save(path)
    c.load(path)
    after = c.pull_sparse("ssd_sv", ids)
    np.testing.assert_allclose(after, before)
    assert c.sparse_stats("ssd_sv")["disk_rows"] > 0


def test_ssd_adagrad_accumulators_survive_spill():
    """The optimizer state spills WITH the row — an adagrad row
    evicted and faulted back must keep its accumulator (identical
    update trajectory vs the in-memory table)."""
    from paddle_tpu.distributed.ps import SSDSparseTable, SparseTable

    mem = SparseTable(4, optimizer="adagrad", lr=0.5, seed=1)
    ssd = SSDSparseTable(4, mem_budget_rows=2, optimizer="adagrad",
                         lr=0.5, seed=1)
    rng = np.random.RandomState(0)
    ids = np.asarray([1, 2, 3, 4, 5])
    for _ in range(6):
        g = rng.randn(5, 4).astype(np.float32)
        mem.push_grad(ids, g)
        ssd.push_grad(ids, g)
        # interleave other ids to force eviction churn
        ssd.pull([7, 8, 9])
        mem.pull([7, 8, 9])
    np.testing.assert_allclose(ssd.pull(ids), mem.pull(ids), rtol=1e-6)
    assert ssd.spill_stats()["spills"] > 0


def test_geo_communicator_syncs_deltas(cluster):
    """Geo-async mode: local updates don't touch the PS until the
    geo_step-th step; after sync the PS table holds the merged
    deltas."""
    from paddle_tpu.distributed.ps import GeoCommunicator

    _, c = cluster
    c.create_sparse_table("geo_t", 4, initializer="zeros")
    geo = GeoCommunicator(c, "geo_t", geo_step=3)
    ids = np.asarray([1, 2, 3])
    rows0 = geo.pull(ids)
    np.testing.assert_allclose(rows0, 0.0)
    g = np.ones((3, 4), np.float32)
    geo.update(ids, g, lr=0.1)
    geo.step()  # 1: no sync yet
    geo.step()  # 2: no sync yet
    # PS still holds zeros (all progress is local)
    np.testing.assert_allclose(c.pull_sparse("geo_t", ids), 0.0)
    geo.update(ids, g, lr=0.1)
    geo.step()  # 3: sync fires
    ps_rows = c.pull_sparse("geo_t", ids)
    np.testing.assert_allclose(ps_rows, -0.2, rtol=1e-6)
    # local cache re-based on the fresh global values
    np.testing.assert_allclose(geo.pull(ids), ps_rows)


def test_geo_two_trainers_merge_additively(cluster):
    """Two geo trainers' deltas SUM on the PS (geo-SGD semantics) —
    neither overwrite nor race."""
    from paddle_tpu.distributed.ps import GeoCommunicator

    _, c = cluster
    c.create_sparse_table("geo_m", 2, initializer="zeros")
    a = GeoCommunicator(c, "geo_m", geo_step=1)
    b = GeoCommunicator(c, "geo_m", geo_step=1)
    ids = np.asarray([5])
    a.pull(ids)
    b.pull(ids)
    a.update(ids, np.full((1, 2), 1.0, np.float32), lr=1.0)
    b.update(ids, np.full((1, 2), 2.0, np.float32), lr=1.0)
    a.step()
    b.step()
    np.testing.assert_allclose(c.pull_sparse("geo_m", ids),
                               [[-3.0, -3.0]])


def test_geo_embedding_training_converges(cluster):
    """End-to-end: DistributedEmbedding over a GeoCommunicator trains
    (loss decreases) and the PS table reflects the progress after
    syncs."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.distributed.ps import GeoCommunicator

    _, c = cluster
    paddle.seed(0)
    geo = GeoCommunicator(c, "geo_e2e", geo_step=4)
    emb = DistributedEmbedding(c, "geo_e2e", num_embeddings=100,
                               emb_dim=8, lr=0.5, communicator=geo)
    head = nn.Linear(8, 1)
    opt = optim.SGD(learning_rate=0.1, parameters=head.parameters())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 100, (32,)).astype(np.int64)
    y = (ids % 2).astype(np.float32).reshape(-1, 1)
    losses = []
    for _ in range(24):
        e = emb(paddle.to_tensor(ids))
        loss = ((head(e) - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        geo.step()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0] * 0.8, losses
    geo.sync()
    assert c.sparse_size("geo_e2e") == len(np.unique(ids))


def test_inmemory_dataset_load_shuffle_partition(tmp_path):
    from paddle_tpu.distributed.ps.dataset import (InMemoryDataset,
                                                   multi_slot_parser)

    # two MultiSlot files: slots "ids" (3 ints) and "label" (1 float)
    rng = np.random.RandomState(0)
    files = []
    for fi in range(2):
        p = tmp_path / f"part-{fi}.txt"
        with open(p, "w") as f:
            for _ in range(50):
                ids = rng.randint(0, 100, 3)
                lbl = rng.rand()
                f.write(f"3 {ids[0]} {ids[1]} {ids[2]} 1 {lbl:.4f}\n")
        files.append(str(p))

    parse = multi_slot_parser(["ids", "label"], ["int64", "float32"])
    full = InMemoryDataset(batch_size=16, thread_num=2, parse_fn=parse)
    assert full.load_into_memory(files) == 100
    s0 = full._samples[0]
    assert s0["ids"].shape == (3,) and s0["label"].shape == (1,)

    # hash-partition global shuffle: disjoint + complete over trainers
    kept = []
    for tid in (0, 1):
        ds = InMemoryDataset(batch_size=16, thread_num=2,
                             parse_fn=parse)
        ds.load_into_memory(files)
        ds.global_shuffle(trainer_id=tid, trainer_num=2)
        kept.append(ds.memory_size())
    assert sum(kept) == 100 and all(k > 0 for k in kept)

    batches = list(full.batches(drop_last=True))
    assert all(len(b) == 16 for b in batches)
    assert len(batches) == 6


def test_dataset_global_shuffle_via_ps(cluster, tmp_path):
    """Data-moving shuffle for disjoint file sets: each trainer ends
    with exactly the samples hashing to it, none lost."""
    from paddle_tpu.distributed.ps.dataset import InMemoryDataset

    _, c = cluster
    files = []
    for fi in range(2):
        p = tmp_path / f"d{fi}.txt"
        with open(p, "w") as f:
            for i in range(30):
                f.write(f"sample-{fi}-{i}\n")
        files.append(str(p))

    results = {}

    def trainer(tid):
        ds = InMemoryDataset(batch_size=8)
        ds.load_into_memory([files[tid]])  # DISJOINT inputs
        ds.global_shuffle_via_ps(c, "shuf", tid, 2)
        results[tid] = list(ds._samples)

    ts = [threading.Thread(target=trainer, args=(tid,))
          for tid in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in ts)
    all_samples = sorted(results[0] + results[1])
    want = sorted(f"sample-{fi}-{i}" for fi in range(2)
                  for i in range(30))
    assert all_samples == want
    assert results[0] and results[1]


def test_downpour_train_from_dataset(cluster, tmp_path):
    """exe.train_from_dataset analog: DownpourTrainer threads consume
    InMemoryDataset batches, pulling/pushing the PS sparse table."""
    from paddle_tpu.distributed.ps.dataset import (InMemoryDataset,
                                                   multi_slot_parser)
    from paddle_tpu.distributed.ps.trainer import (DownpourTrainer,
                                                   TrainerDesc)

    _, c = cluster
    p = tmp_path / "train.txt"
    rng = np.random.RandomState(1)
    with open(p, "w") as f:
        for _ in range(64):
            ids = rng.randint(0, 50, 2)
            f.write(f"2 {ids[0]} {ids[1]} 1 {float(ids[0] % 2)}\n")
    parse = multi_slot_parser(["ids", "label"], ["int64", "float32"])
    ds = InMemoryDataset(batch_size=8, parse_fn=parse)
    ds.load_into_memory([str(p)])
    ds.local_shuffle(seed=0)

    c.create_sparse_table("dft_emb", 4, initializer="zeros")
    trainer = DownpourTrainer(
        TrainerDesc(thread_num=2, async_push=False, lr=0.1), c)
    seen = []

    def train_fn(batch, wid):
        ids = np.concatenate([s["ids"] for s in batch])
        rows = trainer.pull_sparse("dft_emb", ids)
        grads = np.ones_like(rows)
        trainer.push_sparse("dft_emb", ids, grads)
        seen.append(len(batch))

    trainer.train_from_dataset(ds, train_fn, timeout=30)
    assert sum(seen) == 64
    assert c.sparse_size("dft_emb") > 0
    # every touched row stepped by -lr per push it appeared in
    rows = c.pull_sparse("dft_emb", np.arange(50))
    assert (rows <= 0).all()


def test_pipeline_trainer_sections_stream_and_match_serial():
    """SectionWorker/PipelineTrainer (device_worker.h:533,
    section_worker.cc:92-150): results equal the serial composition,
    order preserved, and the stages actually OVERLAP (stage 0
    processes micro-batch k while stage 1 is still on k-1)."""
    import time

    from paddle_tpu.distributed.ps.trainer import PipelineTrainer

    overlap = {"max_inflight": 0, "inflight": 0}
    lock = threading.Lock()

    def make_stage(mult, delay):
        def fn(x, sid):
            with lock:
                overlap["inflight"] += 1
                overlap["max_inflight"] = max(overlap["max_inflight"],
                                              overlap["inflight"])
            time.sleep(delay)
            with lock:
                overlap["inflight"] -= 1
            return x * mult

        return fn

    pt = PipelineTrainer([make_stage(2, 0.01), make_stage(3, 0.01),
                          make_stage(5, 0.01)])
    outs = pt.run(list(range(8)), timeout=30)
    assert outs == [i * 30 for i in range(8)]
    assert overlap["max_inflight"] >= 2  # stages ran concurrently


def test_pipeline_trainer_surfaces_stage_errors():
    from paddle_tpu.distributed.ps.trainer import PipelineTrainer

    def bad(x, sid):
        if x == 3:
            raise ValueError("boom")
        return x

    pt = PipelineTrainer([bad])
    with pytest.raises(RuntimeError, match="boom"):
        pt.run(list(range(5)), timeout=30)


def test_pipeline_trainer_with_ps_embedding(cluster):
    """Industrial shape: stage 0 parses, stage 1 pulls PS rows, stage
    2 reduces — micro-batches stream against the live PS."""
    from paddle_tpu.distributed.ps.trainer import PipelineTrainer

    _, c = cluster
    c.create_sparse_table("pipe_emb", 4, initializer="zeros")
    c.push_sparse("pipe_emb", np.arange(10),
                  -np.ones((10, 4), np.float32), lr=1.0)

    def parse(ids, sid):
        return np.asarray(ids, np.int64)

    def pull(ids, sid):
        return c.pull_sparse("pipe_emb", ids)

    def reduce_(rows, sid):
        return float(rows.sum())

    pt = PipelineTrainer([parse, pull, reduce_])
    outs = pt.run([[1, 2], [3, 4, 5], [9]], timeout=30)
    assert outs == [2 * 4.0, 3 * 4.0, 1 * 4.0]
