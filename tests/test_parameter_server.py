"""Parameter server: tables, RPC, sharding, async communicator,
distributed embedding training (reference:
ps/service/brpc_ps_{client,server}.cc, ps/table/, the_one_ps.py:606)."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (AsyncCommunicator,
                                       DistributedEmbedding, PSClient,
                                       PSServer)


@pytest.fixture()
def cluster():
    servers = [PSServer(server_id=i) for i in range(2)]
    client = PSClient([s.endpoint for s in servers])
    yield servers, client
    client.close()
    for s in servers:
        s.stop()


def test_dense_table_pull_push(cluster):
    _, c = cluster
    c.create_dense_table("w", (4, 3), initializer=np.ones((4, 3)))
    w0 = c.pull_dense("w")
    np.testing.assert_array_equal(w0, 1.0)
    c.push_dense("w", np.full((4, 3), 0.5), lr=1.0)
    np.testing.assert_allclose(c.pull_dense("w"), 0.5)


def test_sparse_table_shard_pull_push(cluster):
    servers, c = cluster
    c.create_sparse_table("emb", emb_dim=4, initializer="zeros")
    ids = np.array([0, 1, 2, 3, 10, 11], np.int64)
    rows = c.pull_sparse("emb", ids)
    assert rows.shape == (6, 4)
    np.testing.assert_array_equal(rows, 0.0)
    # rows landed on both shards (even ids -> server 0, odd -> 1)
    assert servers[0]._sparse["emb"].size() == 3
    assert servers[1]._sparse["emb"].size() == 3
    grads = np.ones((6, 4), np.float32)
    c.push_sparse("emb", ids, grads, lr=0.5)
    np.testing.assert_allclose(c.pull_sparse("emb", ids), -0.5)


def test_sparse_rows_lazily_initialized_deterministic(cluster):
    _, c = cluster
    c.create_sparse_table("e2", emb_dim=8)
    a = c.pull_sparse("e2", [100])
    b = c.pull_sparse("e2", [100])
    np.testing.assert_array_equal(a, b)  # same row on re-pull
    assert np.abs(a).max() > 0  # uniform init, not zeros


def test_save_load_roundtrip(cluster, tmp_path):
    servers, c = cluster
    c.create_sparse_table("e3", emb_dim=2, initializer="zeros")
    c.push_sparse("e3", [1, 2], np.ones((2, 2)), lr=1.0)
    c.save(str(tmp_path / "ckpt"))
    c.push_sparse("e3", [1, 2], np.ones((2, 2)), lr=1.0)  # diverge
    c.load(str(tmp_path / "ckpt"))
    np.testing.assert_allclose(c.pull_sparse("e3", [1, 2]), -1.0)


def test_barrier_two_workers(cluster):
    _, c = cluster
    c2 = PSClient(c._endpoints)
    errs = []

    def other():
        try:
            c2.barrier("sync1", 2, timeout=5)
        except Exception as e:
            errs.append(e)

    t = threading.Thread(target=other)
    t.start()
    c.barrier("sync1", 2, timeout=5)
    t.join(timeout=5)
    assert not errs
    c2.close()


def test_barrier_key_reusable_across_epochs(cluster):
    """The same barrier key must synchronize again next epoch
    (round-2 review: stale counts made later barriers no-ops)."""
    _, c = cluster
    c2 = PSClient(c._endpoints)
    errs = []

    def other(n_epochs):
        try:
            for _ in range(n_epochs):
                c2.barrier("ep", 2, timeout=5)
        except Exception as e:
            errs.append(e)

    t = threading.Thread(target=other, args=(2,))
    t.start()
    c.barrier("ep", 2, timeout=5)
    c.barrier("ep", 2, timeout=5)
    t.join(timeout=10)
    assert not errs
    # epoch 3 with only ONE participant must time out (no stale count)
    with pytest.raises(TimeoutError):
        c.barrier("ep", 2, timeout=0.5)
    c2.close()


def test_save_load_preserves_table_config(cluster, tmp_path):
    """Restore into a fresh server must keep optimizer rule + lr."""
    servers, c = cluster
    c.create_sparse_table("cfg_t", emb_dim=2, optimizer="adagrad",
                          lr=0.01, initializer="zeros")
    c.push_sparse("cfg_t", [4], np.ones((1, 2)))
    c.save(str(tmp_path / "cfg"))
    # wipe server-side tables, then load
    for s in servers:
        s._sparse.clear()
    c.load(str(tmp_path / "cfg"))
    tbl = servers[0]._sparse["cfg_t"]
    assert tbl.optimizer == "adagrad" and tbl.lr == 0.01


def test_distributed_embedding_bounds_check(cluster):
    _, c = cluster
    emb = DistributedEmbedding(c, "bounded", num_embeddings=10,
                               emb_dim=2)
    with pytest.raises(IndexError, match="out of range"):
        emb(np.array([3, 99], np.int64))


def test_async_communicator_flushes(cluster):
    _, c = cluster
    c.create_sparse_table("e4", emb_dim=2, initializer="zeros")
    comm = AsyncCommunicator(c, flush_interval=0.01)
    comm.push_sparse_async("e4", [7], np.ones((1, 2)), lr=1.0)
    comm.stop()  # stop() flushes
    np.testing.assert_allclose(c.pull_sparse("e4", [7]), -1.0)


def test_distributed_embedding_trains(cluster):
    """CTR-style run: PS-hosted embedding + local dense head; loss
    decreases and sparse rows update through the backward hook."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim

    _, c = cluster
    paddle.seed(0)
    emb = DistributedEmbedding(c, "ctr_emb", num_embeddings=1000,
                               emb_dim=8, lr=0.5)
    head = nn.Linear(8, 1)
    opt = optim.SGD(learning_rate=0.1, parameters=head.parameters())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1000, (16,)).astype(np.int64)
    y = (ids % 2).astype(np.float32).reshape(16, 1)

    losses = []
    for _ in range(30):
        e = emb(paddle.to_tensor(ids))
        out = head(e)
        loss = ((out - paddle.to_tensor(y)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.item()))
    assert losses[-1] < losses[0] * 0.8
    assert c.sparse_size("ctr_emb") == len(np.unique(ids))
