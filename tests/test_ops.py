"""Op correctness via the OpTest harness (reference: unittests/test_*_op.py)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.ops import (activation, conv, linalg, loss_ops,
                            manipulation, math as pmath, norm_ops)

from op_test import OpTest

rng = np.random.RandomState(7)


class TestAdd(OpTest):
    op = staticmethod(pmath.add)
    inputs = {"x": rng.rand(3, 4).astype(np.float32),
              "y": rng.rand(3, 4).astype(np.float32)}
    outputs = inputs["x"] + inputs["y"]

    def test(self):
        self.check_output()
        self.check_grad()


class TestMatmul(OpTest):
    op = staticmethod(linalg.matmul)
    inputs = {"x": rng.rand(4, 5).astype(np.float32),
              "y": rng.rand(5, 3).astype(np.float32)}
    outputs = inputs["x"] @ inputs["y"]
    rtol = 1e-4

    def test(self):
        self.check_output()
        self.check_grad()


class TestMatmulTranspose(OpTest):
    op = staticmethod(linalg.matmul)
    inputs = {"x": rng.rand(5, 4).astype(np.float32),
              "y": rng.rand(5, 3).astype(np.float32)}
    attrs = {"transpose_x": True}
    outputs = inputs["x"].T @ inputs["y"]
    rtol = 1e-4

    def test(self):
        self.check_output()


class TestExp(OpTest):
    op = staticmethod(pmath.exp)
    inputs = {"x": rng.rand(10).astype(np.float32)}
    outputs = np.exp(inputs["x"])

    def test(self):
        self.check_output()
        self.check_grad()


class TestSoftmax(OpTest):
    op = staticmethod(activation.softmax)
    inputs = {"x": rng.rand(4, 8).astype(np.float32)}
    x = inputs["x"]
    e = np.exp(x - x.max(-1, keepdims=True))
    outputs = e / e.sum(-1, keepdims=True)

    def test(self):
        self.check_output()
        self.check_grad()


class TestMeanAxis(OpTest):
    op = staticmethod(pmath.mean)
    inputs = {"x": rng.rand(3, 4, 5).astype(np.float32)}
    attrs = {"axis": 1, "keepdim": True}
    outputs = inputs["x"].mean(1, keepdims=True)

    def test(self):
        self.check_output()
        self.check_grad()


class TestReshapeTranspose(OpTest):
    op = staticmethod(manipulation.reshape)
    inputs = {"x": rng.rand(2, 6).astype(np.float32)}
    attrs = {"shape": [3, 4]}
    outputs = inputs["x"].reshape(3, 4)

    def test(self):
        self.check_output()
        self.check_grad()


class TestConcat(OpTest):
    @staticmethod
    def op(x, y, **kw):
        return manipulation.concat([x, y], **kw)

    inputs = {"x": rng.rand(2, 3).astype(np.float32),
              "y": rng.rand(2, 3).astype(np.float32)}
    attrs = {"axis": 1}
    outputs = np.concatenate([inputs["x"], inputs["y"]], axis=1)

    def test(self):
        self.check_output()
        self.check_grad()


class TestLayerNorm(OpTest):
    @staticmethod
    def op(x, w, b, **kw):
        return norm_ops.layer_norm(x, [8], w, b)

    inputs = {"x": rng.rand(4, 8).astype(np.float32),
              "w": np.ones(8, np.float32),
              "b": np.zeros(8, np.float32)}
    x = inputs["x"]
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    outputs = (x - mu) / np.sqrt(var + 1e-5)
    rtol = 1e-4
    atol = 1e-5

    def test(self):
        self.check_output()
        self.check_grad(inputs_to_check=["x"])


class TestCrossEntropy(OpTest):
    @staticmethod
    def op(logits, label, **kw):
        return loss_ops.cross_entropy(logits, label)

    logits = rng.rand(6, 10).astype(np.float32)
    label = rng.randint(0, 10, (6,)).astype(np.int64)
    inputs = {"logits": logits, "label": label}
    lsm = logits - logits.max(-1, keepdims=True)
    lsm = lsm - np.log(np.exp(lsm).sum(-1, keepdims=True))
    outputs = np.float32(-lsm[np.arange(6), label].mean())
    rtol = 1e-4
    atol = 1e-5

    def test(self):
        self.check_output()
        self.check_grad(inputs_to_check=["logits"])


class TestConv2D(OpTest):
    @staticmethod
    def op(x, w, **kw):
        return conv.conv2d(x, w, **kw)

    inputs = {"x": rng.rand(1, 1, 5, 5).astype(np.float32),
              "w": rng.rand(2, 1, 3, 3).astype(np.float32)}
    attrs = {"padding": 1}
    # reference computed with scipy-style direct conv
    x, w = inputs["x"], inputs["w"]
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    out = np.zeros((1, 2, 5, 5), np.float32)
    for oc in range(2):
        for i in range(5):
            for j in range(5):
                out[0, oc, i, j] = (xp[0, 0, i:i + 3, j:j + 3]
                                    * w[oc, 0]).sum()
    outputs = out
    rtol = 1e-4
    atol = 1e-4

    def test(self):
        self.check_output()
        self.check_grad()


class TestTopK(OpTest):
    @staticmethod
    def op(x, **kw):
        return paddle.topk(x, **kw)

    inputs = {"x": np.asarray([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]],
                              np.float32)}
    attrs = {"k": 2}
    outputs = [np.asarray([[3.0, 2.0], [5.0, 4.0]], np.float32),
               np.asarray([[0, 2], [1, 2]], np.int64)]

    def test(self):
        self.check_output()


class TestWhere(OpTest):
    @staticmethod
    def op(c, x, y, **kw):
        return manipulation.where(c, x, y)

    inputs = {"c": np.asarray([True, False, True]),
              "x": np.asarray([1.0, 2.0, 3.0], np.float32),
              "y": np.asarray([9.0, 8.0, 7.0], np.float32)}
    outputs = np.asarray([1.0, 8.0, 3.0], np.float32)

    def test(self):
        self.check_output()
        self.check_grad(inputs_to_check=["x", "y"])


class TestGather(OpTest):
    @staticmethod
    def op(x, idx, **kw):
        return manipulation.gather(x, idx)

    inputs = {"x": rng.rand(5, 3).astype(np.float32),
              "idx": np.asarray([0, 2, 4], np.int64)}
    outputs = inputs["x"][[0, 2, 4]]

    def test(self):
        self.check_output()
        self.check_grad(inputs_to_check=["x"])


class TestCumsum(OpTest):
    op = staticmethod(pmath.cumsum)
    inputs = {"x": rng.rand(3, 4).astype(np.float32)}
    attrs = {"axis": 1}
    outputs = np.cumsum(inputs["x"], axis=1)

    def test(self):
        self.check_output()
        self.check_grad()


class TestPad(OpTest):
    op = staticmethod(manipulation.pad)
    inputs = {"x": rng.rand(1, 1, 3, 3).astype(np.float32)}
    attrs = {"pad": [1, 1, 2, 2]}
    outputs = np.pad(inputs["x"], ((0, 0), (0, 0), (2, 2), (1, 1)))

    def test(self):
        self.check_output()
        self.check_grad()


class TestBatchNormInfer(OpTest):
    @staticmethod
    def op(x, m, v, w, b, **kw):
        out, _, _ = norm_ops.batch_norm(x, m, v, w, b, training=False)
        return out

    inputs = {"x": rng.rand(4, 3, 2, 2).astype(np.float32),
              "m": np.zeros(3, np.float32),
              "v": np.ones(3, np.float32),
              "w": np.ones(3, np.float32),
              "b": np.zeros(3, np.float32)}
    outputs = (inputs["x"] / np.sqrt(1 + 1e-5))
    rtol = 1e-4
    atol = 1e-5

    def test(self):
        self.check_output()


def test_einsum():
    a = paddle.to_tensor(rng.rand(2, 3).astype(np.float32))
    b = paddle.to_tensor(rng.rand(3, 4).astype(np.float32))
    out = paddle.einsum("ij,jk->ik", a, b)
    np.testing.assert_allclose(out.numpy(), a.numpy() @ b.numpy(),
                               rtol=1e-5)


def test_split_stack_unstack():
    x = paddle.to_tensor(rng.rand(6, 4).astype(np.float32))
    parts = paddle.split(x, 3, axis=0)
    assert len(parts) == 3 and parts[0].shape == [2, 4]
    st = paddle.stack(parts, axis=0)
    assert st.shape == [3, 2, 4]
    us = paddle.unstack(st, axis=0)
    assert len(us) == 3


def test_sort_argsort():
    x = paddle.to_tensor([[3.0, 1.0, 2.0]])
    s = paddle.sort(x, axis=-1)
    np.testing.assert_allclose(s.numpy(), [[1, 2, 3]])
    idx = paddle.argsort(x, axis=-1, descending=True)
    np.testing.assert_array_equal(idx.numpy(), [[0, 2, 1]])


def test_linalg_family():
    a_np = rng.rand(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
    a = paddle.to_tensor(a_np)
    inv = paddle.linalg.inv(a) if hasattr(paddle, "linalg") else None
    from paddle_tpu.ops import linalg as L

    np.testing.assert_allclose(L.inv(a).numpy() @ a_np, np.eye(3),
                               atol=1e-4)
    np.testing.assert_allclose(float(L.det(a).item()),
                               float(np.linalg.det(a_np)), rtol=1e-4)
    u, s, vt = L.svd(a)
    np.testing.assert_allclose(
        (u.numpy() * s.numpy()) @ vt.numpy(), a_np, atol=1e-4)


def test_random_reproducible():
    paddle.seed(42)
    a = paddle.randn([4])
    paddle.seed(42)
    b = paddle.randn([4])
    np.testing.assert_allclose(a.numpy(), b.numpy())


def test_reduce_family():
    x = paddle.to_tensor(rng.rand(3, 4).astype(np.float32))
    assert paddle.max(x).numpy() == x.numpy().max()
    np.testing.assert_allclose(paddle.logsumexp(x, axis=1).numpy(),
                               np.log(np.exp(x.numpy()).sum(1)), rtol=1e-5)
    np.testing.assert_allclose(paddle.std(x).numpy(),
                               x.numpy().std(ddof=1), rtol=1e-4)
