"""ISSUE 13: serving resilience — deadlines, load shedding, graceful
drain, and the health-checked multi-replica router.

Three rings over the PR-10 engine, each chaos/e2e-gated:

  * SLO scheduling — deadline expiry at admission (EXPIRED terminal
    state, racing admission), bounded-queue load shedding
    (EngineOverloaded + serve/shed), priority/latest-deadline-aware
    eviction.
  * Lifecycle — drain()/export/import token-exact handoff,
    generate(timeout_s=) raising EngineTimeout with engine state,
    the watchdog incident hook's emergency drain-and-export.
  * Router — least-loaded routing, replica crash AND wedge failover
    replaying in-flight requests TOKEN-IDENTICALLY (the acceptance
    gate: mid-flood replica kill, outputs equal the fault-free
    single-replica run, zero leaked KV blocks, serve/failovers > 0
    in the telemetry snapshot), shed-then-retry on a drained router,
    orphan retention when every replica dies (the PTA073 story).

Every failure-matrix case asserts zero leaked KV blocks via
`check_drained()` + the PTA070 `audit_block_accounting` report.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor as cmon
from paddle_tpu.inference.serving import (EngineOverloaded,
                                          EngineTimeout, LLMEngine,
                                          PagedKVCache, Router,
                                          SamplingParams, Scheduler)
from paddle_tpu.inference.serving.scheduler import (ABORTED, EXPIRED,
                                                    EXPORTED, Request,
                                                    WAITING)
from paddle_tpu.monitor import chaos, flight
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

N_TOKENS = 6
PROMPT_LENS = (3, 9, 5, 12, 7, 4)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, ffn_hidden=128, max_seq_len=64,
                    dropout=0.0, use_flash_attention=False,
                    initializer_range=0.35)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(3)
    return [list(rng.randint(1, 128, n)) for n in PROMPT_LENS]


@pytest.fixture(scope="module")
def want(model, prompts):
    """Fault-free single-replica reference the resilience paths must
    reproduce token-for-token."""
    eng = LLMEngine(model, max_batch=4, block_size=8, num_blocks=32)
    outs = eng.generate(prompts,
                        sampling=SamplingParams(max_new_tokens=N_TOKENS))
    assert eng.check_drained() == {}
    return outs


def sp(**kw):
    kw.setdefault("max_new_tokens", N_TOKENS)
    return SamplingParams(**kw)


def assert_no_leaks(obj):
    """check_drained() + the PTA070 report view, both clean."""
    from paddle_tpu.analysis.serving import audit_block_accounting

    assert obj.check_drained() == {}
    engines = ([r.engine for r in obj._replicas]
               if isinstance(obj, Router) else [obj])
    for eng in engines:
        live = [r.req_id for r in eng._requests.values()
                if not r.finished]
        rep = audit_block_accounting(eng.cache.allocator, live)
        assert rep.findings == [], [f.format() for f in rep.findings]


# ---------------------------------------------------------------------------
# ring (a): deadlines + shedding + victim policy
# ---------------------------------------------------------------------------

class TestDeadlines:
    def test_expiry_racing_admission(self, model, prompts):
        """A request whose deadline passes between add and the next
        admission pass retires EXPIRED at admission — before it takes
        any pool blocks or prefill compute. Zero leaks."""
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32)
        rid = eng.add_request(prompts[0], sp(deadline_s=0.005))
        time.sleep(0.02)
        before = cmon.stat_get("serve/deadline_aborts")
        eng.step()
        req = eng.get_request(rid)
        assert req.state == EXPIRED and req.finished
        assert req.output_ids == []
        assert cmon.stat_get("serve/deadline_aborts") == before + 1
        assert_no_leaks(eng)

    def test_expiry_while_queued_behind_full_batch(self, model,
                                                   prompts):
        """Deadline passes while WAITING behind a full batch: the
        later admission pass (slots free as requests finish) sweeps
        it instead of serving a dead-on-arrival request; live
        requests are untouched."""
        eng = LLMEngine(model, max_batch=1, block_size=8,
                        num_blocks=32)
        slow = eng.add_request(prompts[0], sp())
        doomed = eng.add_request(prompts[1], sp(deadline_s=0.01))
        live = eng.add_request(prompts[2], sp())
        time.sleep(0.03)
        while eng.has_unfinished():
            eng.step()
        from paddle_tpu.inference.serving.scheduler import FINISHED
        assert eng.get_request(doomed).state == EXPIRED
        assert eng.get_request(slow).state == FINISHED
        assert eng.get_request(live).state == FINISHED
        assert len(eng.get_request(live).output_ids) == N_TOKENS
        assert_no_leaks(eng)

    def test_running_requests_are_never_deadline_killed(self, model,
                                                        prompts):
        """A RUNNING request past its deadline finishes: it already
        paid prefill, completing is the cheaper path (the policy the
        scheduler documents)."""
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32)
        rid = eng.add_request(prompts[0], sp(deadline_s=0.05))
        eng.step()                   # admitted before expiry
        time.sleep(0.08)             # expires while RUNNING
        while eng.has_unfinished():
            eng.step()
        assert len(eng.get_request(rid).output_ids) == N_TOKENS
        assert_no_leaks(eng)


class TestLoadShedding:
    def test_queue_bound_sheds(self, model, prompts):
        eng = LLMEngine(model, max_batch=1, block_size=8,
                        num_blocks=32, max_queue=2)
        eng.add_request(prompts[0], sp())
        eng.step()                   # 1 running, queue empty
        eng.add_request(prompts[1], sp())
        eng.add_request(prompts[2], sp())
        before = cmon.stat_get("serve/shed")
        with pytest.raises(EngineOverloaded, match="load shed"):
            eng.add_request(prompts[3], sp())
        assert cmon.stat_get("serve/shed") == before + 1
        while eng.has_unfinished():
            eng.step()
        assert_no_leaks(eng)

    def test_expired_corpses_swept_before_shedding(self, model,
                                                   prompts):
        """A queue full of already-expired entries must not shed live
        traffic: the bound check sweeps expired requests first."""
        eng = LLMEngine(model, max_batch=1, block_size=8,
                        num_blocks=32, max_queue=2)
        eng.add_request(prompts[0], sp())
        eng.step()
        d1 = eng.add_request(prompts[1], sp(deadline_s=0.005))
        d2 = eng.add_request(prompts[2], sp(deadline_s=0.005))
        time.sleep(0.02)
        live = eng.add_request(prompts[3], sp())   # sweeps, no shed
        assert eng.get_request(d1).state == EXPIRED
        assert eng.get_request(d2).state == EXPIRED
        while eng.has_unfinished():
            eng.step()
        assert len(eng.get_request(live).output_ids) == N_TOKENS
        assert_no_leaks(eng)

    def test_env_max_queue(self, monkeypatch):
        from paddle_tpu.inference.serving import env_max_queue

        monkeypatch.setenv("PADDLE_SERVE_MAX_QUEUE", "7")
        assert env_max_queue() == 7
        monkeypatch.setenv("PADDLE_SERVE_MAX_QUEUE", "bogus")
        assert env_max_queue() == 0


class TestVictimPolicy:
    def _sched(self):
        cache = PagedKVCache(2, 4, 16, block_size=4, num_blocks=64)
        return Scheduler(cache, max_batch=4, max_seq_len=64)

    def test_low_priority_evicts_first(self):
        s = self._sched()
        lo = Request([1] * 4, sp(priority=-1))
        hi = Request([1] * 4, sp(priority=5))
        mid = Request([1] * 4, sp())
        for r in (hi, lo, mid):     # admission order != priority
            s.add(r)
        s.schedule()
        assert s._pick_victim() is lo
        s.evict(lo)
        assert s._pick_victim() is mid     # 0 < 5

    def test_latest_deadline_loses_tiebreak(self):
        s = self._sched()
        tight = Request([1] * 4, sp(deadline_s=0.5))
        slack = Request([1] * 4, sp(deadline_s=50.0))
        none = Request([1] * 4, sp())      # no SLO = most slack
        for r in (none, slack, tight):
            s.add(r)
        s.schedule()
        assert s._pick_victim() is none
        s.evict(none)
        assert s._pick_victim() is slack

    def test_default_policy_stays_youngest_first(self):
        """No priorities/deadlines -> the PR-10 vLLM youngest-first
        policy is unchanged."""
        s = self._sched()
        old = Request([1] * 4, sp())
        young = Request([1] * 4, sp())
        s.add(old), s.add(young)
        s.schedule()
        assert s._pick_victim() is young


# ---------------------------------------------------------------------------
# ring (b): lifecycle — drain / export / timeout / incident hook
# ---------------------------------------------------------------------------

class TestDrain:
    def test_drain_completes_running_exports_waiting(self, model,
                                                     prompts, want):
        """drain(): RUNNING requests finish, WAITING export; imports
        on a second engine continue token-identically."""
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32)
        ids = [eng.add_request(p, sp()) for p in prompts[:4]]
        eng.step()                  # 2 running, 2 waiting
        before = cmon.stat_get("serve/drains")
        exports = eng.drain()
        assert cmon.stat_get("serve/drains") == before + 1
        assert [e["req_id"] for e in exports] == ids[2:]
        assert_no_leaks(eng)
        # the two RUNNING requests completed in full
        for i in ids[:2]:
            assert len(eng.get_request(i).output_ids) == N_TOKENS
        # a draining engine sheds new intake
        with pytest.raises(EngineOverloaded, match="draining"):
            eng.add_request(prompts[0], sp())
        # imports replay token-exactly elsewhere
        eng2 = LLMEngine(model, max_batch=2, block_size=8,
                         num_blocks=32)
        for e in exports:
            eng2.import_request(e)
        while eng2.has_unfinished():
            eng2.step()
        got = [eng.get_request(i).output_ids for i in ids[:2]] + \
            [eng2.get_request(i).output_ids for i in ids[2:]]
        assert got == want[:4]
        assert_no_leaks(eng2)

    def test_drain_timeout_exports_running_mid_generation(
            self, model, prompts, want):
        """A drain timeout exports still-RUNNING requests with their
        generated-so-far prefix; replay completes the exact fault-free
        tokens (position-keyed seeds make any prefix resumable)."""
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32)
        ids = [eng.add_request(p,
                               sp(max_new_tokens=N_TOKENS))
               for p in prompts[:2]]
        eng.step()                  # prefill: 1 token each
        exports = eng.drain(timeout_s=0)
        assert [e["req_id"] for e in exports] == ids
        assert all(len(e["output_ids"]) >= 1 for e in exports)
        assert_no_leaks(eng)
        eng2 = LLMEngine(model, max_batch=2, block_size=8,
                         num_blocks=32)
        for e in exports:
            eng2.import_request(e)
        while eng2.has_unfinished():
            eng2.step()
        assert [eng2.get_request(i).output_ids
                for i in ids] == want[:2]
        assert_no_leaks(eng2)

    def test_resume_reopens_admission(self, model, prompts, want):
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32)
        eng.drain()
        eng.resume()
        outs = eng.generate(prompts[:2], sampling=sp())
        assert outs == want[:2]
        assert_no_leaks(eng)

    def test_drain_chaos_raise_leaves_engine_intact(self, model,
                                                    prompts, want):
        """A serve_drain chaos raise aborts the drain BEFORE any
        request is exported: the engine keeps serving, nothing
        leaks, and the retry drains normally."""
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32)
        rid = eng.add_request(prompts[0], sp())
        with chaos.inject("serve_drain", "raise", times=1) as rule:
            with pytest.raises(chaos.ChaosInjected):
                eng.drain()
            assert rule.triggers == 1
        # the aborted drain latched nothing: admission reopens after
        # clearing the half-set draining flag via resume()
        eng.resume()
        while eng.has_unfinished():
            eng.step()
        assert eng.get_request(rid).output_ids == want[0]
        exports = eng.drain()       # retry succeeds
        assert exports == []
        assert_no_leaks(eng)


class TestDrainFenceInterplay:
    def test_drain_returns_emergency_exports_after_mid_drain_fence(
            self, model, prompts, want):
        """If the watchdog incident hook fences the engine mid-drain,
        drain() must fold emergency_exports into its return — [] here
        would read as 'all completed' and the caller would drop the
        in-flight work (the PTA073 class)."""
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32)
        ids = [eng.add_request(p, sp()) for p in prompts[:2]]
        eng.step()
        # simulate the hook firing between drain's dispatches
        eng._incident_export("watchdog")
        exports = eng.drain(timeout_s=1)
        assert [e["req_id"] for e in exports] == ids
        assert eng.emergency_exports is None
        assert_no_leaks(eng)
        eng2 = LLMEngine(model, max_batch=2, block_size=8,
                         num_blocks=32)
        for e in exports:
            eng2.import_request(e)
        while eng2.has_unfinished():
            eng2.step()
        assert [eng2.get_request(i).output_ids
                for i in ids] == want[:2]

    def test_fenced_engine_refuses_intake(self, model, prompts):
        """A fenced engine never steps again — add_request and even
        forced import_request must refuse instead of queueing work
        that strands forever."""
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32)
        rid = eng.add_request(prompts[0], sp())
        eng.step()
        exports = eng.export_requests(fence=True)
        assert [e["req_id"] for e in exports] == [rid]
        with pytest.raises(EngineOverloaded, match="fenced"):
            eng.add_request(prompts[1], sp())
        with pytest.raises(EngineOverloaded, match="fenced"):
            eng.import_request(exports[0], force=True)
        assert_no_leaks(eng)

    def test_router_abort_backs_off_when_step_lock_held(self, model,
                                                        prompts):
        """abort() must not mutate the scheduler unlocked while the
        worker holds the step lock (freed blocks under an in-flight
        dispatch): it raises the retryable EngineOverloaded
        instead."""
        router = Router(model, replicas=1, max_batch=2, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=60.0)
        try:
            rid = router.submit(prompts[0], sp(max_new_tokens=48))
            rep = router._replicas[0]
            assert rep.step_lock.acquire(timeout=10)
            try:
                with pytest.raises(EngineOverloaded, match="busy"):
                    router.abort(rid)
            finally:
                rep.step_lock.release()
            # the documented contract: back off and retry (the hot
            # worker loop re-takes the lock between steps, so one
            # attempt may lose the race repeatedly)
            deadline = time.monotonic() + 30
            while not router.get_request(rid).finished:
                try:
                    router.abort(rid)
                except EngineOverloaded:
                    assert time.monotonic() < deadline
                    time.sleep(0.02)
            assert router.get_request(rid).finished
            assert_no_leaks(router)
        finally:
            router.shutdown()


class TestGenerateTimeout:
    def test_timeout_raises_with_engine_state(self, model, prompts):
        eng = LLMEngine(model, max_batch=1, block_size=8,
                        num_blocks=32)
        with pytest.raises(EngineTimeout) as ei:
            eng.generate(prompts[:3],
                         sampling=sp(max_new_tokens=48),
                         timeout_s=1e-4)
        state = ei.value.engine_state
        assert state["running"] + state["waiting"] >= 1
        assert "heartbeat_age_s" in state and "free_blocks" in state
        # abandoned work is still abortable and leak-free
        for r in list(eng._requests.values()):
            if not r.finished:
                eng.abort_request(r.req_id)
        assert_no_leaks(eng)

    def test_no_timeout_by_default(self, model, prompts, want):
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32)
        assert eng.generate(prompts[:2], sampling=sp()) == want[:2]


class TestIncidentExport:
    def test_watchdog_hook_exports_and_fences(self, model, prompts,
                                              want):
        """The PR-3/6 incident hook path: a watchdog dump on a wedged
        dispatch fences the engine and exports its in-flight work —
        replayable on a healthy engine, token-exactly."""
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32).arm_incident_export()
        try:
            ids = [eng.add_request(p, sp()) for p in prompts[:2]]
            eng.step()
            flight._run_incident_hooks("watchdog")
            assert eng.fenced
            assert eng.step() == {}          # zombie steps no-op
            exports = eng.emergency_exports
            assert [e["req_id"] for e in exports] == ids
            assert_no_leaks(eng)             # exports released blocks
            eng2 = LLMEngine(model, max_batch=2, block_size=8,
                             num_blocks=32)
            for e in exports:
                eng2.import_request(e)
            while eng2.has_unfinished():
                eng2.step()
            assert [eng2.get_request(i).output_ids
                    for i in ids] == want[:2]
            assert_no_leaks(eng2)
        finally:
            eng.disarm_incident_export()

    def test_idle_engine_hook_is_a_noop(self, model):
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32).arm_incident_export()
        try:
            flight._run_incident_hooks("watchdog")
            assert not eng.fenced
            assert eng.emergency_exports is None
        finally:
            eng.disarm_incident_export()


# ---------------------------------------------------------------------------
# ring (c): the multi-replica router
# ---------------------------------------------------------------------------

class TestRouter:
    def test_clean_two_replica_run_matches_reference(self, model,
                                                     prompts, want):
        router = Router(model, replicas=2, max_batch=4, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=60.0)
        try:
            outs = router.generate(prompts, sampling=sp(),
                                   timeout_s=120)
            assert outs == want
            assert_no_leaks(router)
            assert all(router.replica_healthy(i) for i in range(2))
        finally:
            router.shutdown()

    def test_fleet_negotiates_spec_config(self, model, prompts,
                                          want):
        """ISSUE 19: replicas negotiate ONE speculative-decoding
        config at boot — the fleet settles on the weakest replica's
        window, exposes it in state_summary, and a spec+prefix fleet
        still reproduces the plain single-engine reference
        token-for-token."""
        router = Router(model, replicas=2, max_batch=4, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=60.0,
                        spec_k=4, prefix_cache=True)
        try:
            s = router.state_summary()
            assert s["spec_k"] == 4 and s["prefix_cache"] is True
            assert {e["spec_k"] for e in s["engines"]} == {4}
            assert cmon.stat_get("serve/spec/fleet_k") == 4
            outs = router.generate(prompts, sampling=sp(),
                                   timeout_s=120)
            assert outs == want
            assert_no_leaks(router)
        finally:
            router.shutdown()

    def test_least_loaded_routing_by_free_blocks(self, model,
                                                 prompts):
        router = Router(model, replicas=2, max_batch=4, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=60.0)
        try:
            a = router.submit(prompts[3], sp())   # 12 tokens
            b = router.submit(prompts[0], sp())   # 3 tokens
            ra = router._records[a].replica
            rb = router._records[b].replica
            assert ra != rb     # second lands on the emptier replica
            router.wait([a, b], timeout_s=120)
            assert_no_leaks(router)
        finally:
            router.shutdown()

    def test_serve_route_fault_sheds_cleanly(self, model, prompts):
        """A raising serve_route fault fails the submit BEFORE any
        replica is touched: no record, no blocks, retry routes."""
        router = Router(model, replicas=2, max_batch=2, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=60.0)
        try:
            with chaos.inject("serve_route", "raise",
                              times=1) as rule:
                with pytest.raises(chaos.ChaosInjected):
                    router.submit(prompts[0], sp())
                assert rule.triggers == 1
            assert router._records == {}
            assert_no_leaks(router)
            rid = router.submit(prompts[0], sp())   # retry clean
            router.wait([rid], timeout_s=120)
            assert_no_leaks(router)
        finally:
            router.shutdown()

    def test_e2e_failover_gate_replica_crash_mid_flood(
            self, model, prompts, want):
        """THE acceptance gate: 2 replicas, a chaos-injected replica
        kill mid-flood — every request completes with tokens
        identical to the fault-free single-replica run, zero leaked
        KV blocks on all replicas, and serve/failovers > 0 in the
        telemetry snapshot."""
        from paddle_tpu import monitor as pmonitor

        router = Router(model, replicas=2, max_batch=4, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=60.0)
        try:
            before = cmon.stat_get("serve/failovers")
            # a non-OOM decode fault kills the dispatching replica's
            # worker thread mid-flood (after= lets the flood spread
            # over both replicas first)
            with chaos.inject("serve_decode", "raise", after=3,
                              times=1) as rule:
                outs = router.generate(prompts, sampling=sp(),
                                       timeout_s=120)
                assert rule.triggers == 1
            assert outs == want
            snap = pmonitor.telemetry_snapshot()["stats"]
            assert snap["serve/failovers"] >= before + 1
            assert_no_leaks(router)      # dead replica included
            healthy = [i for i in range(2)
                       if router.replica_healthy(i)]
            assert len(healthy) == 1
            gauges = [cmon.stat_get(f"serve/replica/{i}/healthy")
                      for i in range(2)]
            assert sorted(gauges) == [0, 1]
            # the survivor keeps serving
            more = router.generate(prompts[:2], sampling=sp(),
                                   timeout_s=120)
            assert more == want[:2]
        finally:
            router.shutdown()

    def test_failover_preserves_seeded_sampling(self, model,
                                                prompts):
        """Token identity under failover holds for SEEDED temperature
        sampling too — the position-keyed seeds, not greedy argmax,
        carry the determinism."""
        sampling = sp(temperature=0.8, top_k=20, seed=11)
        ref = LLMEngine(model, max_batch=4, block_size=8,
                        num_blocks=32)
        want_s = ref.generate(prompts[:4], sampling=sampling)
        router = Router(model, replicas=2, max_batch=2, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=60.0)
        try:
            with chaos.inject("serve_decode", "raise", after=2,
                              times=1):
                outs = router.generate(prompts[:4], sampling=sampling,
                                       timeout_s=120)
            assert outs == want_s
            assert_no_leaks(router)
        finally:
            router.shutdown()

    @pytest.mark.slow
    def test_wedge_failover_via_heartbeat(self, model, prompts,
                                          want):
        """A replica wedged INSIDE a dispatch (chaos stall) stops
        stamping heartbeats; the router declares it dead after
        heartbeat_timeout_s and replays its requests — the zombie
        thread waking later no-ops against the fence."""
        router = Router(model, replicas=2, max_batch=2, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=5.0)
        try:
            # warm both replicas' compiled programs first so a
            # first-dispatch XLA compile can't read as a wedge
            assert router.generate(prompts, sampling=sp(),
                                   timeout_s=120) == want
            before = cmon.stat_get("serve/failovers")
            with chaos.inject("serve_decode", "stall", secs=300,
                              after=2, times=1):
                outs = router.generate(prompts, sampling=sp(),
                                       timeout_s=120)
            assert outs == want
            assert cmon.stat_get("serve/failovers") == before + 1
            assert_no_leaks(router)
        finally:
            router.shutdown()

    def test_heartbeat_never_retires_last_replica(self, model,
                                                  prompts, want):
        """The cascade backstop: a stale heartbeat on the LAST
        healthy replica (e.g. a slow first-bucket compile after
        absorbing a failover) must NOT retire it — the slow-but-alive
        replica finishes instead of the fleet dying."""
        router = Router(model, replicas=1, max_batch=2, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=0.2)
        try:
            before = cmon.stat_get("serve/failovers")
            with chaos.inject("serve_decode", "stall", secs=1.0,
                              after=1, times=1):
                outs = router.generate(prompts[:2], sampling=sp(),
                                       timeout_s=120)
            assert outs == want[:2]
            assert cmon.stat_get("serve/failovers") == before
            assert router.replica_healthy(0)
            assert_no_leaks(router)
        finally:
            router.shutdown()

    def test_shed_then_retry_on_drained_router(self, model, prompts,
                                               want):
        """Drain the fleet -> submits shed (EngineOverloaded with
        router state attached) -> resume -> the retry serves. Zero
        leaks throughout."""
        router = Router(model, replicas=2, max_batch=2, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=60.0)
        try:
            rid = router.submit(prompts[0], sp())
            # wait for the worker to admit + prefill before draining,
            # so the request is RUNNING (drain completes it) rather
            # than still WAITING (drain would export it)
            t0 = time.monotonic()
            while not router.get_request(rid).output_ids \
                    and time.monotonic() - t0 < 60:
                time.sleep(0.005)
            exports = router.drain(timeout_s=60)
            # the running request completed inside the drain window
            assert exports == []
            assert router.get_request(rid).output_ids == want[0]
            before = cmon.stat_get("serve/shed")
            with pytest.raises(EngineOverloaded) as ei:
                router.submit(prompts[1], sp())
            # every healthy replica shed once before the router gave up
            assert cmon.stat_get("serve/shed") == before + 2
            assert ei.value.engine_state["healthy"] == 2
            assert_no_leaks(router)
            router.resume()
            outs = router.generate(prompts[:2], sampling=sp(),
                                   timeout_s=120)
            assert outs == want[:2]
            assert_no_leaks(router)
        finally:
            router.shutdown()

    def test_all_replicas_dead_retains_orphans(self, model, prompts):
        """When the LAST replica dies the un-replayable exports are
        retained in orphan_exports (never silently dropped — the
        PTA073 contract) and wait() raises."""
        router = Router(model, replicas=1, max_batch=2, block_size=8,
                        num_blocks=32, heartbeat_timeout_s=60.0)
        try:
            ids = [router.submit(p, sp(max_new_tokens=24))
                   for p in prompts[:2]]
            with chaos.inject("serve_decode", "raise", after=1,
                              times=1):
                with pytest.raises(RuntimeError,
                                   match="no healthy"):
                    router.wait(ids, timeout_s=60)
            assert len(router.orphan_exports) == 2
            assert {e["req_id"] for e in router.orphan_exports} == \
                set(ids)
            assert_no_leaks(router)  # exports released their blocks
        finally:
            router.shutdown()

    def test_env_knobs(self, monkeypatch):
        from paddle_tpu.inference.serving import (env_heartbeat_s,
                                                  env_replicas)

        monkeypatch.setenv("PADDLE_SERVE_REPLICAS", "3")
        monkeypatch.setenv("PADDLE_SERVE_HEARTBEAT_S", "2.5")
        assert env_replicas() == 3
        assert env_heartbeat_s() == 2.5
        monkeypatch.setenv("PADDLE_SERVE_REPLICAS", "junk")
        monkeypatch.setenv("PADDLE_SERVE_HEARTBEAT_S", "junk")
        assert env_replicas() == 1
        assert env_heartbeat_s() == 10.0


# ---------------------------------------------------------------------------
# chaos sites + PTA073
# ---------------------------------------------------------------------------

class TestChaosSites:
    def test_new_sites_registered(self):
        assert "serve_route" in chaos.SITES
        assert "serve_drain" in chaos.SITES

    def test_sites_listed_by_cli_surface(self):
        # the chaos spec grammar accepts the new sites
        rules = chaos.parse_spec(
            "serve_route:raise;serve_drain:delay:ms=1")
        assert [r.site for r in rules] == ["serve_route",
                                           "serve_drain"]


class TestPTA073:
    def test_discarded_export_flagged(self):
        from paddle_tpu.analysis.serving import lint_kv_source

        src = ("def failover(self, rep):\n"
               "    rep.engine.export_requests(fence=True)\n")
        rep = lint_kv_source(src, filename="x.py")
        assert [f.code for f in rep.findings] == ["PTA073"]

    def test_assigned_but_never_read_flagged(self):
        from paddle_tpu.analysis.serving import lint_kv_source

        src = ("def failover(self, rep):\n"
               "    exports = rep.engine.export_requests()\n"
               "    rep.dead = True\n")
        rep = lint_kv_source(src, filename="x.py")
        assert [f.code for f in rep.findings] == ["PTA073"]

    def test_readded_or_returned_exports_clean(self):
        from paddle_tpu.analysis.serving import lint_kv_source

        good_readd = ("def failover(self, rep, target):\n"
                      "    exports = rep.engine.export_requests()\n"
                      "    for e in exports:\n"
                      "        target.import_request(e)\n")
        good_return = ("def drain(self):\n"
                       "    exports = self.export_requests()\n"
                       "    return exports\n")
        for src in (good_readd, good_return):
            assert lint_kv_source(src, filename="x.py").findings == []

    def test_router_and_engine_sources_clean(self):
        """The failover/drain implementations satisfy their own
        lint — every export path re-adds, returns, or retains."""
        import os

        from paddle_tpu.analysis.cli import iter_target_files, \
            lint_file
        from paddle_tpu.analysis.diagnostics import Report

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        rep = Report()
        target = os.path.join(repo, "paddle_tpu", "inference",
                              "serving")
        for path in iter_target_files(target):
            lint_file(path, rep, sanitize=("serving",))
        assert not rep.findings, [f.format() for f in rep.findings]


class TestStateTransitions:
    def test_exported_and_expired_are_terminal(self):
        r = Request([1, 2], sp())
        for state in (EXPIRED, EXPORTED, ABORTED):
            r.state = state
            assert r.finished
        r.state = WAITING
        assert not r.finished

    def test_import_preserves_deadline_and_evictions(self, model,
                                                     prompts):
        eng = LLMEngine(model, max_batch=2, block_size=8,
                        num_blocks=32)
        rid = eng.add_request(prompts[0], sp(deadline_s=30.0))
        eng.step()
        req = eng.get_request(rid)
        req.evictions = 2
        deadline = req.deadline
        exports = eng.export_requests()
        eng2 = LLMEngine(model, max_batch=2, block_size=8,
                         num_blocks=32)
        eng2.import_request(exports[0])
        r2 = eng2.get_request(rid)
        assert r2.deadline == deadline     # absolute SLO survives
        assert r2.evictions == 2
        assert r2.output_ids == req.output_ids
        assert_no_leaks(eng2.scheduler and eng2)
