"""Worker script for the subprocess distributed harness (reference:
test_dist_base.py TestDistRunnerBase.run_trainer — each rank trains
the same model and reports per-step losses for the parent to compare).

Runs standalone: reads the PADDLE_* env contract (absent = 1-process),
trains a tiny data-parallel GPT over the global device mesh, writes
per-rank losses as JSON to <out_prefix>.rank<r>.
"""
import json
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.optimizer as optim  # noqa: E402
from paddle_tpu.distributed import (build_mesh, get_rank,  # noqa: E402
                                    init_parallel_env, set_mesh)
from paddle_tpu.jit.distributed import (  # noqa: E402
    DistributedTrainStepCompiler)
from paddle_tpu.text.models.gpt import (GPTConfig,  # noqa: E402
                                        GPTForCausalLM)


def main(out_prefix):
    init_parallel_env()
    paddle.seed(0)
    mesh = build_mesh({"dp": -1})
    set_mesh(mesh)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, ffn_hidden=64, max_seq_len=16,
                    remat=False, use_flash_attention=False, dropout=0.0)
    model = GPTForCausalLM(cfg)
    opt = optim.AdamW(learning_rate=1e-3, parameters=model.parameters())
    step = DistributedTrainStepCompiler(model, opt, mesh=mesh)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 128, (8, 16)).astype(np.int32))
    losses = [float(step(ids, ids).item()) for _ in range(3)]
    with open(f"{out_prefix}.rank{get_rank()}", "w") as f:
        json.dump(losses, f)
    print(f"rank {get_rank()} losses {losses}", flush=True)

    # eager cross-process collectives (multihost_utils path): each rank
    # contributes rank+1; the all_reduce must return the WORLD sum on
    # every rank (r1 weak #10: the single-controller identity would be
    # silently wrong multi-process)
    if jax.process_count() > 1:
        from paddle_tpu.distributed import all_reduce, broadcast

        t = paddle.to_tensor(
            np.array([float(get_rank() + 1)], np.float32))
        all_reduce(t)
        b = paddle.to_tensor(
            np.array([float(get_rank() * 100)], np.float32))
        broadcast(b, src=0)
        with open(f"{out_prefix}.coll{get_rank()}", "w") as f:
            json.dump({"allreduce": float(t.numpy()[0]),
                       "broadcast": float(b.numpy()[0])}, f)


if __name__ == "__main__":
    main(sys.argv[1])
