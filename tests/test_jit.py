"""jit/to_static + TrainStepCompiler tests (reference:
dygraph_to_static test family)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
from paddle_tpu.jit import TrainStepCompiler, to_static


def test_to_static_function():
    @to_static
    def f(x):
        return x * 2.0 + 1.0

    x = paddle.to_tensor([1.0, 2.0])
    out = f(x)
    np.testing.assert_allclose(out.numpy(), [3.0, 5.0])
    # second call hits the cache
    out2 = f(paddle.to_tensor([3.0, 4.0]))
    np.testing.assert_allclose(out2.numpy(), [7.0, 9.0])


def test_to_static_layer_method():
    net = nn.Linear(4, 2)
    st = to_static(lambda x: net(x))
    x = paddle.randn([3, 4])
    eager = net(x).numpy()
    compiled = st(x).numpy()
    np.testing.assert_allclose(compiled, eager, rtol=1e-5, atol=1e-6)


def test_to_static_matches_after_param_update():
    net = nn.Linear(2, 2)
    st = to_static(lambda x: net(x))
    x = paddle.randn([1, 2])
    _ = st(x)
    net.weight.set_value(np.zeros((2, 2), np.float32))
    out = st(x).numpy()
    np.testing.assert_allclose(out, np.broadcast_to(net.bias.numpy(),
                                                    (1, 2)), rtol=1e-5)


def test_train_step_compiler_convergence():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 1))
    loss_fn = nn.MSELoss()
    o = opt.Adam(learning_rate=0.01, parameters=net.parameters())
    step = TrainStepCompiler(net, o, lambda out, y: loss_fn(out, y))
    x = paddle.randn([32, 4])
    w_true = paddle.randn([4, 1])
    y = paddle.matmul(x, w_true)
    losses = [float(step(x, y).item()) for _ in range(80)]
    assert losses[-1] < losses[0] * 0.1


def test_train_step_compiler_matches_eager():
    paddle.seed(3)
    net_a = nn.Linear(3, 1)
    net_b = nn.Linear(3, 1)
    net_b.set_state_dict(net_a.state_dict())
    loss_fn = nn.MSELoss()
    x = paddle.randn([8, 3])
    y = paddle.randn([8, 1])

    oa = opt.SGD(learning_rate=0.1, parameters=net_a.parameters())
    la = loss_fn(net_a(x), y)
    la.backward()
    oa.step()

    ob = opt.SGD(learning_rate=0.1, parameters=net_b.parameters())
    step = TrainStepCompiler(net_b, ob, lambda out, yy: loss_fn(out, yy))
    lb = step(x, y)
    np.testing.assert_allclose(float(la.item()), float(lb.item()),
                               rtol=1e-5)
    np.testing.assert_allclose(net_a.weight.numpy(), net_b.weight.numpy(),
                               rtol=1e-4, atol=1e-6)


def test_trace_mode_blocks_numpy():
    from paddle_tpu.core import engine

    @to_static
    def f(x):
        return paddle.to_tensor(x.numpy())  # illegal under trace

    with pytest.raises(Exception):
        f(paddle.to_tensor([1.0]))


def test_jit_save_load(tmp_path):
    import paddle_tpu.jit as jit

    net = nn.Linear(2, 2)
    path = str(tmp_path / "model")
    jit.save(net, path, input_spec=[jit.InputSpec([None, 2], "float32")])
    loaded = jit.load(path)
    sd = loaded.state_dict()
    np.testing.assert_allclose(sd["weight"].numpy(), net.weight.numpy())
    x = paddle.rand([3, 2])
    np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                               rtol=1e-6)


def test_digest_cache_evicts_single_entry_not_whole_memo():
    """Overflow evicts ONE entry (dead weakref preferred, else the
    oldest) and counts it — the old behavior clear()'d the whole memo,
    re-hashing every live static table on the next call."""
    import paddle_tpu.jit as jit
    from paddle_tpu.core import monitor as cm

    jit._digest_cache.clear()
    keep = [np.full((4,), i, np.float32)
            for i in range(jit._DIGEST_CACHE_MAX + 3)]  # refs stay live
    before = cm.stat_get("jit/digest_cache/evictions")
    for a in keep:
        jit._freeze_static(a)
    # never wholesale-cleared: the memo sits at capacity, 3 evictions
    assert len(jit._digest_cache) == jit._DIGEST_CACHE_MAX
    assert cm.stat_get("jit/digest_cache/evictions") == before + 3
    # most-recent entries survived and still memo-hit
    ent = jit._digest_cache.get(id(keep[-1]))
    assert ent is not None and ent[0]() is keep[-1]
    key_again = jit._freeze_static(keep[-1])
    assert key_again is ent[1]
    # dead-weakref entries are evicted before live ones
    jit._digest_cache.clear()
    a = np.ones((2,), np.float32)
    b = np.ones((3,), np.float32)
    tmp = np.ones((4,), np.float32)
    jit._freeze_static(a)
    jit._freeze_static(tmp)
    jit._freeze_static(b)
    tmp_id = id(tmp)
    del tmp  # its cache entry's weakref goes dead
    jit._digest_cache_evict_one()
    assert tmp_id not in jit._digest_cache
    assert id(a) in jit._digest_cache  # older LIVE entry survived
    assert id(b) in jit._digest_cache
    jit._digest_cache.clear()
