"""static/passes.py pass-framework tests: registry error contract,
apply_pass version-bump cache invalidation, transitive liveness in
DeadOpEliminationPass, and the AnalysisPass read-only contract."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static.passes import (AnalysisPass,
                                      DeadOpEliminationPass, Pass,
                                      PassRegistry, apply_pass,
                                      live_op_slice, register_pass,
                                      registry)


@pytest.fixture(autouse=True)
def _static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def _fresh():
    return static.Program(), static.Program()


def test_registry_duplicate_name_raises():
    r = PassRegistry()

    class P1(Pass):
        pass

    r.register("p", P1)
    with pytest.raises(ValueError, match="already registered"):
        r.register("p", P1)


def test_registry_unknown_name_raises_with_known_list():
    r = PassRegistry()

    class P1(Pass):
        pass

    r.register("alpha", P1)
    with pytest.raises(KeyError, match="alpha"):
        r.get("nonexistent")


def test_global_registry_has_builtin_and_analysis_passes():
    names = registry.names()
    assert "dead_op_elimination" in names
    assert "op_substitution" in names
    # the analysis suite registers alongside the rewrites
    assert "dead_var_analysis" in names
    assert "unfetched_output_analysis" in names
    assert "op_coverage_analysis" in names


def test_register_pass_decorator_sets_name():
    r_name = "tmp_test_pass_xyz"

    @register_pass(r_name)
    class TmpPass(Pass):
        def apply(self, program):
            return program

    try:
        assert TmpPass.name == r_name
        assert isinstance(registry.get(r_name), TmpPass)
    finally:
        registry._passes.pop(r_name, None)


def test_apply_pass_version_bump_invalidates_replay_cache():
    """An op-substitution applied AFTER a run takes effect on the
    next run because apply_pass bumps the program version keyed into
    the Executor cache."""
    from paddle_tpu.static.passes import OpSubstitutionPass

    main, startup = _fresh()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 2], "float32")
        y = paddle.nn.functional.relu(x)
    exe = static.Executor()
    xv = np.ones((2, 2), np.float32)
    v0 = getattr(main, "_version", 0)
    o1, = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(o1, 1.0)
    n_cached = len(exe._cache)
    apply_pass(main, OpSubstitutionPass().configure(
        "relu", lambda v: v * 7.0))
    assert main._version == v0 + 1
    o2, = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(o2, 7.0)
    # a NEW cache entry was compiled (old one not silently reused)
    assert len(exe._cache) == n_cached + 1


def test_dead_op_elimination_transitive_in_one_application():
    """One application keeps the transitively-LIVE chain intact and
    drops the whole transitively-DEAD chain."""
    main, startup = _fresh()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 2], "float32")
        # live chain: x -> a -> b -> out
        a = paddle.exp(x)
        b = a * 2.0
        out = b + 1.0
        # dead chain: x -> d1 -> d2 (nothing consumes d2)
        d1 = paddle.tanh(x)
        d2 = d1 * 3.0  # noqa: F841
    assert len(main.global_block().ops) == 5
    apply_pass(main, DeadOpEliminationPass(keep_vars=[out]))
    kept_types = [op.type for op in main.global_block().ops]
    assert len(kept_types) == 3
    assert "tanh" not in kept_types
    exe = static.Executor()
    xv = np.zeros((2, 2), np.float32)
    o, = exe.run(main, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(o, 3.0)  # exp(0)*2+1


def test_dead_op_elimination_empty_roots_raises():
    main, startup = _fresh()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 2], "float32")
        _ = paddle.exp(x)
    with pytest.raises(ValueError, match="no roots"):
        apply_pass(main, DeadOpEliminationPass())


def test_live_op_slice_shared_helper():
    main, startup = _fresh()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 2], "float32")
        a = paddle.exp(x)
        out = a * 2.0
        dead = paddle.tanh(x)  # noqa: F841
    kept, live = live_op_slice(main, [out])
    assert [op.type for op in kept] == ["exp", "multiply"]
    assert id(x) in live  # inputs of live ops join the live set
    # read-only: the program still holds all three ops
    assert len(main.global_block().ops) == 3


def test_analysis_pass_is_read_only_and_stashes_findings():
    class CountOps(AnalysisPass):
        def analyze(self, program):
            from paddle_tpu.analysis import Finding

            n = len(program.global_block().ops)
            return [Finding("PTA012", f"{n} ops", severity="info")]

    main, startup = _fresh()
    with static.program_guard(main, startup):
        x = static.data("x", [2, 2], "float32")
        _ = paddle.nn.functional.relu(x)
    v0 = getattr(main, "_version", 0)
    p = CountOps()
    out = apply_pass(main, p)
    assert out is main
    assert len(main.global_block().ops) == 1
    assert getattr(main, "_version", 0) == v0  # no version bump
    assert p.last_findings[0].message == "1 ops"
