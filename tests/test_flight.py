"""Flight recorder + hang/crash forensics (paddle_tpu.monitor.flight
+ the `python -m paddle_tpu.monitor` CLI) — the failure-time black box
the reference stack provides via VLOG trails and distributed hang
dumps: a stalled collective must produce a per-rank watchdog dump
(stacks + flight-ring tail + telemetry snapshot) without hanging the
suite, an unhandled exception must leave an inspectable crash bundle,
and per-rank chrome traces must merge into one Perfetto file."""
import glob
import json
import os
import re
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import monitor
from paddle_tpu.monitor import flight
from paddle_tpu.monitor.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_flight(tmp_path, monkeypatch):
    """Every test gets its own dump dir and a fresh ring; watchdog and
    excepthook are always torn down."""
    monkeypatch.setenv("PADDLE_FLIGHT_DIR", str(tmp_path))
    flight.recorder.clear()
    yield
    flight.stop_watchdog()
    flight.uninstall_excepthook()
    flight.uninstall_signal_handler()
    # uninstall-while-wrapped deliberately retains the original hook
    # so a live chain keeps terminating; between tests the chain is
    # gone, so drop the retained state for full isolation
    flight._orig_excepthook = None
    flight._orig_threading_hook = None
    flight._orig_sig_handler = None
    flight._orig_sig_signum = None


def _wait_for(pred, timeout=10.0, step=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


# ---------------------------------------------------------------------------
# ring
# ---------------------------------------------------------------------------

def test_ring_records_and_drops_oldest():
    rec = flight.FlightRecorder(capacity=16, enabled=True)
    for i in range(40):
        rec.record("ev", i=i)
    t = rec.tail()
    assert len(t) == 16
    assert [e["i"] for e in t] == list(range(24, 40))  # oldest dropped
    assert rec.stats()["dropped"] == 24
    assert rec.tail(3) == t[-3:]


def test_ring_disabled_is_noop():
    rec = flight.FlightRecorder(capacity=16, enabled=False)
    rec.record("ev")
    assert rec.tail() == []


def test_tail_zero_means_none_not_all():
    rec = flight.FlightRecorder(capacity=16, enabled=True)
    for i in range(4):
        rec.record("ev", i=i)
    assert rec.tail(0) == []  # PADDLE_FLIGHT_DUMP_EVENTS=0 -> empty
    assert len(rec.tail(None)) == 4


def test_ring_drop_counter_in_registry(monkeypatch):
    monitor.stat_reset()
    monkeypatch.setattr(flight, "recorder",
                        flight.FlightRecorder(capacity=16, enabled=True))
    for i in range(20):
        flight.record("spin", i=i)
    # registry gauges are amortized on the hot path; any snapshot
    # consumer (exporter/bench/dumps) syncs through this call
    flight.sync_stats()
    assert monitor.stat_get("flight/events") == 20
    assert monitor.stat_get("flight/ring/dropped") == 4


def test_in_flight_registry_begin_end():
    with flight.in_flight("collective", "all_reduce", bytes=256,
                          group="world"):
        entries = flight.inflight_snapshot()
        assert any(e["name"] == "all_reduce"
                   and e["kind"] == "collective" for e in entries)
    assert not any(e["name"] == "all_reduce"
                   for e in flight.inflight_snapshot())
    kinds = [e["kind"] for e in flight.tail()]
    assert "collective_begin" in kinds and "collective_end" in kinds
    endev = [e for e in flight.tail()
             if e["kind"] == "collective_end"][-1]
    assert endev["dur_us"] >= 0


def test_in_flight_cleared_on_exception():
    with pytest.raises(RuntimeError):
        with flight.in_flight("collective", "broadcast"):
            raise RuntimeError("mid-collective")
    assert flight.inflight_snapshot() == []


def test_jit_build_failure_clears_inflight(monkeypatch):
    """A failed to_static build must not leak its in-flight compile
    entry — the watchdog would report it as a permanent hang and it
    would pollute every later dump's in_flight section."""
    from paddle_tpu.jit import StaticFunction, to_static

    @to_static
    def f(x):
        return x + 1

    def boom(self, *a, **k):
        raise RuntimeError("build-fail")

    monkeypatch.setattr(StaticFunction, "_build", boom)
    with pytest.raises(RuntimeError, match="build-fail"):
        f(paddle.to_tensor(np.ones((2,), np.float32)))
    assert flight.inflight_snapshot() == []
    kinds = [e["kind"] for e in flight.tail()]
    assert "compile_begin" in kinds and "compile_end" in kinds


def test_collective_flight_event_positional_group():
    """The flight event records the REAL group even when it is passed
    positionally (group sits at a different position per collective) —
    a 'world' mislabel would point the post-mortem at all ranks."""
    import paddle_tpu.distributed as dist

    g = dist.new_group([0])
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    dist.all_reduce(t, dist.ReduceOp.SUM, g)
    begins = [e for e in flight.tail()
              if e["kind"] == "collective_begin"
              and e["name"] == "all_reduce"]
    assert begins and begins[-1]["group"] == [0]
    assert begins[-1]["bytes"] == 2 * 2 * 4


# ---------------------------------------------------------------------------
# watchdog on a stalled collective
# ---------------------------------------------------------------------------

def test_watchdog_dumps_stalled_collective(tmp_path):
    """Acceptance: a deliberately stalled fake collective (through the
    REAL _instrumented hook) triggers a per-rank dump with all-thread
    stacks, the flight-ring tail and a telemetry snapshot — within the
    timeout, without hanging the suite."""
    from paddle_tpu.distributed import collective as coll

    release = threading.Event()
    entered = threading.Event()

    @coll._instrumented("fake_stall")
    def stalled_collective(tensor=None, group=None):
        entered.set()
        release.wait(30)

    monitor.stat_reset()
    t = threading.Thread(target=stalled_collective, daemon=True,
                         name="stalled-collective")
    wd = flight.start_watchdog(timeout_s=0.3, poll_s=0.05)
    try:
        t.start()
        assert entered.wait(5)
        assert _wait_for(lambda: glob.glob(
            str(tmp_path / "watchdog_rank0_*.json")))
    finally:
        release.set()
        flight.stop_watchdog()
        t.join(5)

    dumps = glob.glob(str(tmp_path / "watchdog_rank0_*.json"))
    assert dumps, "watchdog wrote no dump"
    bundle = json.load(open(dumps[0]))
    assert bundle["schema"] == flight.DUMP_SCHEMA
    assert bundle["reason"] == "watchdog"
    assert bundle["rank"] == 0 and bundle["pid"] == os.getpid()
    # the stuck op is named, with its age past the timeout
    stuck = bundle["stuck"]
    assert any(e["name"] == "fake_stall"
               and e["kind"] == "collective"
               and e["age_s"] > 0.3 for e in stuck)
    # all-thread stacks include the stalled thread parked in wait()
    stacks = "".join(line for th in bundle["threads"]
                     for line in th["stack"])
    assert "release.wait" in stacks or "stalled_collective" in stacks
    names = {th["name"] for th in bundle["threads"]}
    assert "stalled-collective" in names
    # flight tail shows the collective entering but never exiting
    kinds = [e["kind"] for e in bundle["flight_tail"]]
    assert "collective_begin" in kinds
    begin = next(e for e in bundle["flight_tail"]
                 if e["kind"] == "collective_begin")
    assert begin["name"] == "fake_stall"
    # telemetry snapshot embedded
    assert "stats" in bundle["telemetry"]
    assert wd.fired >= 1
    assert monitor.stat_get("flight/watchdog/fires") >= 1
    assert monitor.stat_get("flight/dumps_written") >= 1


def test_watchdog_reports_each_stuck_op_once():
    tok = flight.begin("collective", "wedged")
    wd = flight.Watchdog(timeout_s=0.01, poll_s=10)
    try:
        now = time.monotonic() + 1  # ages ride the monotonic clock
        assert wd.check(now=now) is not None
        assert wd.check(now=now + 1) is None  # same op: no re-dump
        assert wd.fired == 1
    finally:
        flight.end(tok)
    assert wd.check(now=time.monotonic() + 5) is None  # done: quiet


def test_watchdog_retries_after_failed_dump(monkeypatch):
    """A dump write failing (full disk) must NOT permanently suppress
    the evidence — the op stays unreported and the next poll retries."""
    tok = flight.begin("collective", "wedged-nodisk")
    wd = flight.Watchdog(timeout_s=0.01, poll_s=10)
    calls = []

    def flaky_dump(reason, extra=None, path=None):
        calls.append(reason)
        if len(calls) == 1:
            raise OSError("disk full")
        return "/fake/dump.json"

    monkeypatch.setattr(flight, "write_dump", flaky_dump)
    try:
        now = time.monotonic() + 1
        with pytest.raises(OSError):
            wd.check(now=now)
        assert wd.fired == 0
        assert wd.check(now=now) == "/fake/dump.json"  # retried
        assert wd.fired == 1
    finally:
        flight.end(tok)


def test_watchdog_ignores_fast_ops():
    wd = flight.Watchdog(timeout_s=60, poll_s=10)
    with flight.in_flight("collective", "quick"):
        assert wd.check() is None
    assert wd.fired == 0


# ---------------------------------------------------------------------------
# crash bundles
# ---------------------------------------------------------------------------

def test_excepthook_writes_inspectable_bundle(tmp_path, capsys):
    flight.install_excepthook()
    try:
        try:
            raise ValueError("boom-forensics")
        except ValueError:
            sys.excepthook(*sys.exc_info())
    finally:
        flight.uninstall_excepthook()
    # the original traceback still printed (hook chains, not replaces)
    assert "boom-forensics" in capsys.readouterr().err
    dumps = glob.glob(str(tmp_path / "crash_rank0_*.json"))
    assert len(dumps) == 1
    bundle = json.load(open(dumps[0]))
    assert bundle["reason"] == "crash"
    assert bundle["exception"]["type"] == "ValueError"
    assert "boom-forensics" in bundle["exception"]["message"]
    assert any("boom-forensics" in line
               for line in bundle["exception"]["traceback"])
    # the exception event reached the flight ring
    assert any(e["kind"] == "exception"
               for e in bundle["flight_tail"])
    assert bundle["env"]  # PADDLE_FLIGHT_DIR at minimum
    assert isinstance(bundle["jit_caches"], list)


def test_excepthook_install_idempotent_and_restores():
    orig = sys.excepthook
    flight.install_excepthook()
    flight.install_excepthook()
    assert sys.excepthook is flight._flight_excepthook
    assert flight._orig_excepthook is orig
    flight.uninstall_excepthook()
    assert sys.excepthook is orig


def test_excepthook_no_cycle_when_wrapped_and_rearmed(tmp_path,
                                                      capsys):
    """fit arms; a third-party hook wraps ours; fit arms AGAIN — the
    second install must be a no-op (flag-guarded), or crash-time
    dispatch cycles ours -> wrapper -> ours forever, writing a dump
    per recursion level."""
    flight.install_excepthook()
    inner = sys.excepthook
    calls = []

    def wrapper(etype, value, tb):
        calls.append("wrapper")
        inner(etype, value, tb)

    sys.excepthook = wrapper
    try:
        flight.install_excepthook()  # re-arm (e.g. second fit call)
        try:
            raise ValueError("wrapped-crash")
        except ValueError:
            sys.excepthook(*sys.exc_info())
        # exactly ONE bundle, wrapper ran once, no recursion
        assert len(glob.glob(str(tmp_path / "crash_rank0_*.json"))) \
            == 1
        assert calls == ["wrapper"]
        assert "wrapped-crash" in capsys.readouterr().err
    finally:
        sys.excepthook = wrapper  # fixture's uninstall handles flags
        flight.uninstall_excepthook()
        sys.excepthook = sys.__excepthook__


def test_worker_thread_crash_writes_bundle(tmp_path):
    """An unhandled exception on a WORKER thread routes through
    threading.excepthook, not sys.excepthook — the armed layer must
    still leave a bundle."""
    flight.install_excepthook()
    try:
        def die():
            raise RuntimeError("worker-died")

        t = threading.Thread(target=die, name="doomed-worker",
                             daemon=True)
        t.start()
        t.join(5)
        assert _wait_for(lambda: glob.glob(
            str(tmp_path / "crash_rank0_*.json")), timeout=5)
    finally:
        flight.uninstall_excepthook()
    bundle = json.load(
        open(glob.glob(str(tmp_path / "crash_rank0_*.json"))[0]))
    assert bundle["exception"]["type"] == "RuntimeError"
    assert "worker-died" in bundle["exception"]["message"]


def test_dump_on_crash_context_manager(tmp_path):
    with pytest.raises(RuntimeError):
        with flight.dump_on_crash():
            raise RuntimeError("worker-thread crash")
    dumps = glob.glob(str(tmp_path / "crash_rank0_*.json"))
    assert dumps
    bundle = json.load(open(dumps[0]))
    assert bundle["exception"]["type"] == "RuntimeError"


@pytest.mark.skipif(not hasattr(__import__("signal"), "SIGUSR1"),
                    reason="no SIGUSR1 on this platform")
def test_sigusr1_live_dump_chains_prior_handler(tmp_path):
    import signal as _signal

    seen = []
    prior = lambda s, f: seen.append(s)  # noqa: E731
    old = _signal.signal(_signal.SIGUSR1, prior)
    try:
        assert flight.install_signal_handler()
        os.kill(os.getpid(), _signal.SIGUSR1)
        # the dump runs on a helper thread (the handler itself must
        # not take locks the interrupted frame may hold)
        assert _wait_for(lambda: glob.glob(
            str(tmp_path / "sigusr1_rank0_*.json")), timeout=5)
        # the application's own handler still ran (preemption
        # checkpoint triggers must not be eaten by auto-arm)
        assert seen == [_signal.SIGUSR1]
        flight.uninstall_signal_handler()
        assert _signal.getsignal(_signal.SIGUSR1) is prior
    finally:
        _signal.signal(_signal.SIGUSR1, old)
    bundle = json.load(
        open(glob.glob(str(tmp_path / "sigusr1_rank0_*.json"))[0]))
    assert bundle["reason"] == "sigusr1"
    assert bundle["threads"]


@pytest.mark.skipif(not hasattr(__import__("signal"), "SIGUSR1"),
                    reason="no SIGUSR1 on this platform")
def test_install_signal_handler_one_signal_at_a_time():
    import signal as _signal

    assert flight.install_signal_handler()           # SIGUSR1
    assert flight.install_signal_handler()           # same: still ok
    # a DIFFERENT signal is refused, not silently "succeeded"
    assert flight.install_signal_handler(_signal.SIGUSR2) is False
    assert _signal.getsignal(_signal.SIGUSR2) \
        is not flight._signal_handler
    flight.uninstall_signal_handler()


def test_rank_in_dump_filename(monkeypatch, tmp_path):
    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    path = flight.write_dump("manual")
    assert os.path.basename(path).startswith("manual_rank3_")
    assert json.load(open(path))["rank"] == 3


# ---------------------------------------------------------------------------
# arming
# ---------------------------------------------------------------------------

def test_maybe_auto_arm_distributed_default(monkeypatch):
    orig_hook = sys.excepthook
    # single-process, no explicit gate: stays off
    monkeypatch.delenv("PADDLE_FLIGHT_AUTOARM", raising=False)
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    assert flight.maybe_auto_arm("test") is None
    assert sys.excepthook is orig_hook
    # distributed: on by default
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")
    try:
        wd = flight.maybe_auto_arm("test")
        assert wd is not None and wd.running()
        assert sys.excepthook is flight._flight_excepthook
    finally:
        flight.stop_watchdog()
        flight.uninstall_excepthook()
    # explicit off wins even when distributed
    monkeypatch.setenv("PADDLE_FLIGHT_AUTOARM", "0")
    assert flight.maybe_auto_arm("test") is None
    # any non-falsy value forces on (the _env_on contract), even
    # single-process
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    monkeypatch.setenv("PADDLE_FLIGHT_AUTOARM", "yes")
    try:
        assert flight.maybe_auto_arm("test") is not None
    finally:
        flight.stop_watchdog()
        flight.uninstall_excepthook()


def test_arm_skips_watchdog_when_flight_disabled(monkeypatch):
    """PADDLE_FLIGHT_ENABLE=0: begin() registers nothing, so arm()
    must not spawn a watchdog thread that polls an empty table
    forever; crash dumps still install."""
    orig_hook = sys.excepthook
    monkeypatch.setattr(flight.recorder, "enabled", False)
    try:
        assert flight.arm() is None
        assert flight.get_watchdog() is None
        assert sys.excepthook is flight._flight_excepthook
    finally:
        flight.uninstall_excepthook()
    assert sys.excepthook is orig_hook


def test_fit_auto_arm_gated_on(monkeypatch):
    """Model.fit arms the forensics layer when PADDLE_FLIGHT_AUTOARM=1
    — the same call distributed runs get by default."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import TensorDataset

    monkeypatch.setenv("PADDLE_FLIGHT_AUTOARM", "1")
    net = nn.Linear(4, 2)
    model = Model(net)
    model.prepare(optimizer=optim.SGD(learning_rate=1e-2,
                                      parameters=net.parameters()),
                  loss=nn.MSELoss())
    xs = paddle.to_tensor(np.ones((4, 4), np.float32))
    ys = paddle.to_tensor(np.ones((4, 2), np.float32))
    try:
        model.fit(TensorDataset([xs, ys]), epochs=1, batch_size=2,
                  verbose=0)
        wd = flight.get_watchdog()
        assert wd is not None and wd.running()
        assert sys.excepthook is flight._flight_excepthook
        assert any(e["kind"] == "auto_arm" and
                   e["where"] == "hapi.Model.fit"
                   for e in flight.tail())
    finally:
        flight.stop_watchdog()
        flight.uninstall_excepthook()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_cli_inspect_json_roundtrip(tmp_path, capsys):
    try:
        raise KeyError("lost-key")
    except KeyError:
        path = flight._crash_dump(*sys.exc_info())
    assert cli_main(["inspect", path, "--json"]) == 0
    out = capsys.readouterr().out
    bundle = json.loads(out)  # machine-readable round trip
    assert bundle["schema"] == flight.DUMP_SCHEMA
    assert bundle["exception"]["type"] == "KeyError"
    # pretty mode renders the same bundle
    assert cli_main(["inspect", path, "--stacks"]) == 0
    pretty = capsys.readouterr().out
    assert "KeyError" in pretty and "flight tail" in pretty


def _fake_trace(path, rank):
    with open(path, "w") as f:
        json.dump({"traceEvents": [
            {"name": "hapi/train_step", "cat": "TrainStep", "ph": "X",
             "ts": 10.0 + rank, "dur": 5.0, "pid": 0, "tid": 7},
            {"name": "fusion", "ph": "X", "ts": 11.0, "dur": 2.0,
             "pid": 1000, "tid": 1},
            {"name": "loss", "ph": "C", "ts": 12.0, "pid": 0,
             "args": {"value": 0.25}},
        ]}, f)


def test_cli_merge_traces(tmp_path, capsys):
    """Acceptance: merge-traces emits ONE chrome trace from >= 2
    per-rank inputs, with disjoint pid spaces and rank labels."""
    p0 = tmp_path / "trace_rank0.json"
    p1 = tmp_path / "trace_rank1.json"
    _fake_trace(p0, 0)
    _fake_trace(p1, 1)
    out = tmp_path / "merged.json"
    assert cli_main(["merge-traces", "-o", str(out),
                     str(p0), str(p1)]) == 0
    merged = json.load(open(out))
    evs = merged["traceEvents"]
    spans = [e for e in evs if e.get("ph") == "X"]
    assert len(spans) == 4  # 2 per rank
    pids = {e["pid"] for e in evs}
    # rank 0 keeps pid 0/1000; rank 1 shifts by the stride
    assert {0, 1000, 100000, 101000} <= pids
    # per-rank events carry their rank in args
    r1 = [e for e in spans if e["pid"] >= 100000]
    assert all(e["args"]["rank"] == 1 for e in r1)
    # Perfetto process labels present
    meta = [e for e in evs if e.get("ph") == "M"
            and e.get("name") == "process_name"]
    labels = {e["args"]["name"] for e in meta}
    assert {"rank0 host", "rank1 host"} <= labels
    assert merged["metadata"]["merged_ranks"] == [0, 1]


def test_cli_merge_traces_rank_from_position(tmp_path):
    """No rankN token in the filename: argument order assigns ranks."""
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    _fake_trace(a, 0)
    _fake_trace(b, 1)
    out = tmp_path / "m.json"
    assert cli_main(["merge-traces", "-o", str(out), str(a),
                     str(b)]) == 0
    assert json.load(open(out))["metadata"]["merged_ranks"] == [0, 1]


def test_cli_merge_traces_rejects_duplicate_ranks(tmp_path, capsys):
    """rank1-from-filename colliding with rank1-from-position must
    refuse rather than silently interleave two ranks' pid spaces."""
    a = tmp_path / "trace_rank1.json"
    b = tmp_path / "other.json"  # position 1 -> also rank 1
    _fake_trace(a, 1)
    _fake_trace(b, 1)
    out = tmp_path / "m.json"
    assert cli_main(["merge-traces", "-o", str(out), str(a),
                     str(b)]) == 2
    assert "duplicate rank" in capsys.readouterr().err
    assert not out.exists()
    # an embedded 'rank' token inside a word is NOT a rank label
    from paddle_tpu.monitor.cli import _rank_of

    assert _rank_of("crank2.json", 7) == 7
    assert _rank_of("metrics_rank3.json", 0) == 3


def test_cli_merge_traces_widens_stride_for_real_pids(tmp_path,
                                                      capsys):
    """An input pid >= the stride (real OS pids) must not bleed into
    the next rank's shifted block — the stride widens automatically."""
    paths = []
    for r in (0, 1):
        p = tmp_path / f"trace_rank{r}.json"
        with open(p, "w") as f:
            json.dump({"traceEvents": [
                {"name": "span", "ph": "X", "ts": 1, "dur": 1,
                 "pid": 123456, "tid": 1}]}, f)
        paths.append(str(p))
    out = tmp_path / "m.json"
    assert cli_main(["merge-traces", "-o", str(out)] + paths) == 0
    assert "widening stride" in capsys.readouterr().err
    merged = json.load(open(out))
    assert merged["metadata"]["pid_stride"] == 1000000
    pids = sorted(e["pid"] for e in merged["traceEvents"]
                  if e.get("ph") == "X")
    assert pids == [123456, 1123456]  # disjoint per-rank blocks


def test_cli_tail_summarizes_exporter_output(tmp_path, capsys):
    from paddle_tpu import monitor as umon

    monitor.stat_reset()
    monitor.stat_add("step/count", 7)
    path = tmp_path / "metrics.jsonl"
    exp = umon.MetricsExporter(str(path), interval=3600)
    exp.flush()
    monitor.stat_add("step/count", 1)
    exp.flush()
    assert cli_main(["tail", str(path)]) == 0
    out = capsys.readouterr().out
    assert "2 flushes" in out
    assert "step/count = 8" in out
    assert cli_main(["tail", str(path), "--all"]) == 0


def test_cli_clean_error_on_bad_input(tmp_path, capsys):
    """Missing or non-JSON inputs print `error: ...` and exit 2 (the
    analysis-CLI contract) instead of dumping a traceback."""
    assert cli_main(["inspect",
                     str(tmp_path / "missing.json")]) == 2
    assert "error:" in capsys.readouterr().err
    bad = tmp_path / "bad.json"
    bad.write_text("{truncated")
    assert cli_main(["inspect", str(bad)]) == 2
    out = tmp_path / "m.json"
    assert cli_main(["merge-traces", "-o", str(out),
                     str(bad)]) == 2
    assert cli_main(["tail", str(tmp_path / "missing.jsonl")]) == 2
    # a hand-filtered bundle with a kind-less tail event still renders
    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps(
        {"reason": "crash", "flight_tail": [{"ts": 1.0}]}))
    assert cli_main(["inspect", str(partial)]) == 0


def test_cli_module_entrypoint():
    """`python -m paddle_tpu.monitor --help` is wired."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.monitor", "--help"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr
    for sub in ("inspect", "merge-traces", "tail"):
        assert sub in proc.stdout


# ---------------------------------------------------------------------------
# doc drift: every PADDLE_* env var in monitor code is in the README
# ---------------------------------------------------------------------------

def test_jax_ready_probe_attributes_exist():
    """_jax_ready reads private jax attributes; pin them so a jax
    upgrade that moves them fails THIS test instead of silently
    disabling the side-effect-free rank/world probes (which would
    quietly stop auto-arm on jax-native multi-host)."""
    from jax._src import distributed as jdist
    from jax._src import xla_bridge

    assert hasattr(xla_bridge, "_backends")
    assert hasattr(jdist, "global_state")
    from paddle_tpu.distributed.env import _jax_ready

    assert isinstance(_jax_ready(), bool)


def test_cli_merge_traces_preserves_input_process_names(tmp_path):
    """Input traces that already label a pid (XPlane device names)
    keep that label (rank-prefixed) — a synthesized generic label
    would win in viewers that take the last process_name per pid."""
    p = tmp_path / "trace_rank1.json"
    with open(p, "w") as f:
        json.dump({"traceEvents": [
            {"name": "fusion", "ph": "X", "ts": 1, "dur": 1,
             "pid": 1000, "tid": 1},
            {"ph": "M", "name": "process_name", "pid": 1000,
             "args": {"name": "/device:TPU:0"}},
        ]}, f)
    out = tmp_path / "m.json"
    assert cli_main(["merge-traces", "-o", str(out), str(p)]) == 0
    evs = json.load(open(out))["traceEvents"]
    labels = [e["args"]["name"] for e in evs if e.get("ph") == "M"
              and e.get("name") == "process_name"
              and e.get("pid") == 101000]
    assert labels == ["rank1 /device:TPU:0"]


def test_monitor_env_vars_documented_in_readme():
    """CI gate (the test_analysis_selfcheck pattern): every PADDLE_*
    env var the monitor stack — plus the io/jit/hapi performance
    knobs (PADDLE_IO_DEVICE_PREFETCH, PADDLE_JIT_STEPS_PER_DISPATCH)
    and the device/memory surface (monitor/memory.py,
    device/__init__.py: PADDLE_MEM_*) — reads must appear in the
    README env-var table — new knobs can't ship undocumented."""
    files = glob.glob(os.path.join(REPO, "paddle_tpu", "monitor*.py"))
    files += glob.glob(
        os.path.join(REPO, "paddle_tpu", "monitor", "*.py"))
    files += glob.glob(os.path.join(REPO, "paddle_tpu", "io", "*.py"))
    files += glob.glob(os.path.join(REPO, "paddle_tpu", "jit", "*.py"))
    files += glob.glob(os.path.join(REPO, "paddle_tpu", "hapi", "*.py"))
    files += glob.glob(
        os.path.join(REPO, "paddle_tpu", "device", "*.py"))
    # elastic checkpointing (the PADDLE_CKPT_* / EDL env contract)
    files += glob.glob(
        os.path.join(REPO, "paddle_tpu", "incubate", "checkpoint",
                     "*.py"))
    # fused Pallas kernel library + fused optimizer entry
    # (PADDLE_PALLAS_* — ISSUE 8)
    files += glob.glob(
        os.path.join(REPO, "paddle_tpu", "incubate", "nn", "pallas",
                     "*.py"))
    files += glob.glob(
        os.path.join(REPO, "paddle_tpu", "optimizer", "*.py"))
    # sanitizer suite (PADDLE_SANITIZE — ISSUE 10): monitor/sanitize.py
    # is already covered by the monitor glob; extend over analysis/ so
    # static-pass knobs can't ship undocumented either
    files += glob.glob(
        os.path.join(REPO, "paddle_tpu", "analysis", "*.py"))
    assert files, "monitor sources not found"
    pat = re.compile(r"PADDLE_[A-Z0-9_]+")
    used = set()
    for fp in files:
        with open(fp) as f:
            used |= set(pat.findall(f.read()))
    with open(os.path.join(REPO, "README.md")) as f:
        documented = set(pat.findall(f.read()))
    missing = sorted(used - documented)
    assert not missing, (
        f"env vars referenced in paddle_tpu/monitor/ but missing from "
        f"the README table: {missing}")
