"""EMA / LookAhead / ModelAverage tests (r4 verdict missing #4) —
numpy-referenced updates + state_dict round-trips.

Reference semantics: fluid/optimizer.py ExponentialMovingAverage,
incubate/optimizer/lookahead.py, incubate/optimizer/modelaverage.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as optim


def _mk(seed=3):
    paddle.seed(seed)
    return nn.Linear(4, 3)


def _train_step(model, opt_or_cb, x, y):
    pred = model(paddle.to_tensor(x))
    loss = paddle.mean((pred - paddle.to_tensor(y)) ** 2)
    loss.backward()
    if callable(getattr(opt_or_cb, "step", None)):
        opt_or_cb.step()
        opt_or_cb.clear_grad()
    return float(loss.item())


def test_ema_matches_numpy_reference():
    model = _mk()
    opt = optim.SGD(learning_rate=0.05,
                    parameters=model.parameters())
    decay = 0.9
    ema = optim.ExponentialMovingAverage(model.parameters(),
                                         decay=decay)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 3).astype(np.float32)
    shadows = [np.zeros_like(np.asarray(p._value), np.float32)
               for p in model.parameters()]
    T = 5
    for t in range(T):
        _train_step(model, opt, x, y)
        ema.update()
        for i, p in enumerate(model.parameters()):
            shadows[i] = decay * shadows[i] + (1 - decay) * np.asarray(
                p._value, np.float32)
    raw = [np.asarray(p._value).copy() for p in model.parameters()]
    with ema.apply():
        corr = 1.0 - decay ** T  # bias correction (reference eq.)
        for p, s in zip(model.parameters(), shadows):
            np.testing.assert_allclose(np.asarray(p._value), s / corr,
                                       rtol=1e-5, atol=1e-6)
    for p, r in zip(model.parameters(), raw):  # restored
        np.testing.assert_allclose(np.asarray(p._value), r, rtol=0,
                                   atol=0)


def test_ema_thres_steps_schedules_decay():
    model = _mk()
    ema = optim.ExponentialMovingAverage(model.parameters(), decay=0.999,
                                         thres_steps=lambda: 0.0)
    # min(0.999, (1+0)/(10+0)) = 0.1
    assert abs(ema._decay_t() - 0.1) < 1e-9


def test_ema_scheduled_decay_bias_correction_exact():
    """thres_steps schedules the APPLIED decay per update, so the
    bias correction must be 1 - prod(d_i), not 1 - decay**t — the old
    decay**t form divided early-scheduled EMAs by ~1/900th of the
    right correction and inflated applied parameters (ADVICE high)."""
    model = _mk()
    steps = iter([0.0, 5.0, 50.0, 1e6, 1e6])
    ema = optim.ExponentialMovingAverage(model.parameters(), decay=0.999,
                                         thres_steps=lambda: next(steps))
    shadows = {id(p): np.zeros_like(np.asarray(p._value), np.float32)
               for p in model.parameters()}
    prod = 1.0
    for t, ts in enumerate([0.0, 5.0, 50.0, 1e6, 1e6]):
        d = min(0.999, (1.0 + ts) / (10.0 + ts))
        prod *= d
        for p in model.parameters():
            shadows[id(p)] = d * shadows[id(p)] + (1 - d) * np.asarray(
                p._value, np.float32)
        ema.update()
    corr = 1.0 - prod  # ~0.9998 — decay**5 correction would be ~0.005
    with ema.apply():
        for p in model.parameters():
            np.testing.assert_allclose(np.asarray(p._value),
                                       shadows[id(p)] / corr,
                                       rtol=1e-5, atol=1e-6)


def test_ema_state_dict_roundtrip_preserves_corr_prod():
    model = _mk()
    ema = optim.ExponentialMovingAverage(model.parameters(), decay=0.9,
                                         thres_steps=lambda: 0.0)
    ema.update()  # applied decay 0.1, NOT 0.9
    sd = ema.state_dict()
    assert abs(sd["corr_prod"] - 0.1) < 1e-12
    ema2 = optim.ExponentialMovingAverage(model.parameters(), decay=0.9)
    ema2.set_state_dict(sd)
    assert abs(ema2._corr_prod - 0.1) < 1e-12
    # legacy checkpoint without corr_prod: falls back to decay**t
    legacy = {k: v for k, v in sd.items() if k != "corr_prod"}
    ema3 = optim.ExponentialMovingAverage(model.parameters(), decay=0.9)
    ema3.set_state_dict(legacy)
    assert abs(ema3._corr_prod - 0.9) < 1e-12


def test_ema_state_dict_roundtrip():
    model = _mk()
    ema = optim.ExponentialMovingAverage(model.parameters(), decay=0.9)
    for p in model.parameters():
        p._grad = None
    ema.update()
    sd = ema.state_dict()
    ema2 = optim.ExponentialMovingAverage(model.parameters(), decay=0.9)
    ema2.set_state_dict(sd)
    for p in model.parameters():
        np.testing.assert_allclose(np.asarray(ema2._shadow[id(p)]),
                                   np.asarray(ema._shadow[id(p)]))
    assert ema2._t == ema._t


def test_lookahead_matches_numpy_reference():
    model = _mk()
    inner = optim.SGD(learning_rate=0.1, parameters=model.parameters())
    alpha, k = 0.5, 2
    la = optim.LookAhead(inner, alpha=alpha, k=k)
    rng = np.random.RandomState(1)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 3).astype(np.float32)

    # numpy mirror of fast/slow dynamics with plain SGD
    slow = [np.asarray(p._value, np.float32).copy()
            for p in model.parameters()]
    fast = [s.copy() for s in slow]

    def np_grads(ws):
        # linear layer: pred = x@W + b; loss = mean((pred-y)^2)
        W, b = ws
        pred = x @ W + b
        g = 2.0 * (pred - y) / pred.size
        return [x.T @ g, g.sum(0)]

    for t in range(1, 5):
        gW, gb = np_grads(fast)
        fast[0] = fast[0] - 0.1 * gW
        fast[1] = fast[1] - 0.1 * gb
        if t % k == 0:
            for i in range(2):
                slow[i] = slow[i] + alpha * (fast[i] - slow[i])
                fast[i] = slow[i].copy()
        _train_step(model, la, x, y)
        for p, f in zip(model.parameters(), fast):
            np.testing.assert_allclose(np.asarray(p._value), f,
                                       rtol=1e-4, atol=1e-5)


def test_lookahead_state_dict_roundtrip():
    model = _mk()
    la = optim.LookAhead(
        optim.Momentum(learning_rate=0.1, momentum=0.9,
                       parameters=model.parameters()), alpha=0.3, k=3)
    rng = np.random.RandomState(2)
    x = rng.randn(4, 4).astype(np.float32)
    y = rng.randn(4, 3).astype(np.float32)
    for _ in range(4):
        _train_step(model, la, x, y)
    sd = la.state_dict()
    la2 = optim.LookAhead(
        optim.Momentum(learning_rate=0.1, momentum=0.9,
                       parameters=model.parameters()), alpha=0.9, k=7)
    la2.set_state_dict(sd)
    assert la2.alpha == 0.3 and la2.k == 3 and la2._la_step == 4
    for p in model.parameters():
        np.testing.assert_allclose(np.asarray(la2._slow[id(p)]),
                                   np.asarray(la._slow[id(p)]))


def test_model_average_matches_numpy_reference():
    model = _mk()
    inner = optim.SGD(learning_rate=0.05,
                      parameters=model.parameters())
    ma = optim.ModelAverage(0.5, parameters=model.parameters(),
                            min_average_window=2,
                            max_average_window=10,
                            inner_optimizer=inner)
    rng = np.random.RandomState(3)
    x = rng.randn(8, 4).astype(np.float32)
    y = rng.randn(8, 3).astype(np.float32)
    history = []
    for _ in range(3):
        _train_step(model, ma, x, y)
        history.append([np.asarray(p._value, np.float32).copy()
                        for p in model.parameters()])
    # window: num_accumulates restarts per the reference condition —
    # replicate it
    sums = [np.zeros_like(h) for h in history[0]]
    num_acc = 0
    for t, snap in enumerate(history, start=1):
        num_acc += 1
        for i, arr in enumerate(snap):
            sums[i] = sums[i] + arr
        limit = min(10, max(int(t * 0.5), 1))
        if num_acc >= 2 and num_acc >= limit:
            num_acc = 1
            sums = [arr.copy() for arr in snap]
    raw = [np.asarray(p._value).copy() for p in model.parameters()]
    with ma.apply():
        for p, s in zip(model.parameters(), sums):
            np.testing.assert_allclose(np.asarray(p._value),
                                       s / max(num_acc, 1),
                                       rtol=1e-5, atol=1e-6)
    for p, r in zip(model.parameters(), raw):
        np.testing.assert_allclose(np.asarray(p._value), r)


def test_model_average_state_dict_roundtrip():
    model = _mk()
    ma = optim.ModelAverage(0.5, parameters=model.parameters(),
                            min_average_window=2, max_average_window=10)
    ma.accumulate()
    sd = ma.state_dict()
    ma2 = optim.ModelAverage(0.5, parameters=model.parameters(),
                             min_average_window=2, max_average_window=10)
    ma2.set_state_dict(sd)
    assert ma2._num_accumulates == ma._num_accumulates
    for p in model.parameters():
        np.testing.assert_allclose(np.asarray(ma2._sum[id(p)]),
                                   np.asarray(ma._sum[id(p)]))


def test_incubate_exports():
    import paddle_tpu.incubate as incubate

    assert incubate.LookAhead is optim.LookAhead
    assert incubate.optimizer.ModelAverage is optim.ModelAverage
