"""Benchmark entry (driver contract): prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}.

Covers all five BASELINE.md configs:
  1. MNIST LeNet        — imgs/s, compiled train step (f32)
  2. ResNet-50          — imgs/s, SGD+momentum, O2 bf16 (BN stays f32)
  3. BERT-base pretrain — tokens/s, Pallas flash-attention path
  4. GPT-2 345M         — tokens/s (flagship; the headline metric)
  5. ERNIE hybrid       — tokens/s through DistributedTrainStepCompiler
                          (mp+pp machinery; single-chip mesh here)

All half-precision configs use the reference's O2 numerics: bf16
weights with fp32 master weights in the optimizer
(multi_precision=True), norm layers kept f32 via amp.decorate. Every
config asserts its loss decreased over the measured window.

vs_baseline ratios use documented V100 stand-ins (BASELINE.md: the
reference repo publishes no numbers, so these constants are the
recorded "CUDAPlace/V100" proxies; north star >= 1/1.2 of them):
  GPT-2 345M fp16   ~12,000 tokens/s/GPU (Megatron-LM V100 measurements)
  ResNet-50 AMP     ~780 imgs/s/GPU (MLPerf-era V100 fp16)
  BERT-base fp16    ~25,000 tokens/s/GPU (NVIDIA BERT repo, seq 512)
  ERNIE-base fp16   ~25,000 tokens/s/GPU (BERT-base-shaped proxy)
  LeNet MNIST       ~10,000 imgs/s (dygraph dispatch-bound V100 proxy)
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINES = {
    "gpt2_345m": 12000.0,
    "resnet50": 780.0,
    "resnet50_pipeline": 780.0,
    "bert_base": 25000.0,
    "ernie": 25000.0,
    "mnist_lenet": 10000.0,
}


WINDOWS = 5  # median-of-k windows (r3 weak #1: single windows showed
# ±20-80% cross-run spread through the tunnel; the median of five
# independent windows is the recorded number and the spread is reported)


def _measure(step, args, steps, warmup):
    """Median of WINDOWS timing windows, `steps` timed steps each.
    Returns (dt_per_step, first_loss, last_loss, window_dts)."""
    for _ in range(warmup):
        loss = step(*args)
    first = float(loss.item())
    dts = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = step(*args)
        last = float(loss.item())  # .item() syncs
        dts.append((time.perf_counter() - t0) / steps)
    return float(np.median(dts)), first, last, dts


def peak_tflops():
    """Peak bf16 chip TF/s for the MFU column. BENCH_PEAK_TFLOPS
    still wins (back-compat with older trail records), otherwise the
    monitor/perf device-kind table supplies it — the SAME source the
    per-program MFU in extra.perf uses, so the two columns can never
    disagree on the peak (ISSUE 16)."""
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    try:
        from paddle_tpu.monitor import perf as _perf

        return float(_perf.device_peaks()["peak_tflops"])
    except Exception:
        return 197.0  # v5e dense bf16, the historical default


def _param_count(model):
    return sum(int(np.prod(p.shape)) for p in model.parameters())


def _mfu(flops_per_step, dt):
    """Model FLOPs utilization against peak_tflops(). For transformers
    flops = 6*N*tokens (param FLOPs, fwd+bwd); convnets use published
    per-image forward GFLOPs x3."""
    return round(flops_per_step / dt / (peak_tflops() * 1e12), 4)


def _pack(value, unit, dts, mfu=None, program=None, flops=None):
    r = {"value": value, "unit": unit,
         "window_spread": [round(d, 6) for d in dts]}
    if mfu is not None:
        r["mfu"] = mfu
    if program is not None:
        # ties the config row to its perf/program/* ledger entry so
        # extra.perf can price analytic-vs-compiler FLOPs drift
        r["program"] = program
        r["analytic_flops_per_step"] = flops
    return r


def _check_decreasing(name, first, last):
    assert np.isfinite(last), f"{name}: non-finite loss {last}"
    assert last < first, (
        f"{name}: loss did not decrease over the bench window "
        f"({first:.4f} -> {last:.4f})")


def bench_mnist(on_tpu):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.vision.models import LeNet

    # r3 probe: the step is host-latency-bound through the tunnel
    # (B=256 step ~2.5 ms compute but high run-to-run jitter). B=1024 +
    # >=60 timed steps x 5 windows amortizes it (r3 weak #1).
    paddle.seed(0)
    batch = 1024 if on_tpu else 32
    steps, warmup = (100, 5) if on_tpu else (3, 1)
    net = LeNet()
    ce = nn.CrossEntropyLoss()
    opt = optim.Adam(learning_rate=1e-3, parameters=net.parameters())
    step = TrainStepCompiler(net, opt,
                             lambda o, y: ce(o, y))
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(batch, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 10, (batch,)).astype(np.int64))
    dt, first, last, dts = _measure(step, (x, y), steps, warmup)
    _check_decreasing("mnist", first, last)
    # LeNet fwd ~= 0.00042 GF/img (published MACs x2), fwd+bwd ~3x
    fl = 3 * 0.00042e9 * batch
    r = _pack(round(batch / dt, 1), "imgs/s", dts, _mfu(fl, dt),
              program=step._perf_name, flops=fl)
    r["note"] = ("dispatch/tunnel latency probe: at this model size "
                 "the number measures the harness round-trip, not the "
                 "framework — do not read vs_baseline as a win "
                 "(r4 verdict weak #5)")

    # fused multi-step dispatch: K train steps scanned through ONE XLA
    # program (steps_per_dispatch) — the per-step host round-trip this
    # probe is bound by amortizes over K, so the ratio
    # fused/vs-unfused IS the dispatch overhead the r5 verdict flagged
    K = 8
    paddle.seed(0)
    net_f = LeNet()
    opt_f = optim.Adam(learning_rate=1e-3,
                       parameters=net_f.parameters())
    step_f = TrainStepCompiler(net_f, opt_f, lambda o, y: ce(o, y),
                               steps_per_dispatch=K)
    xs = paddle.to_tensor(
        rng.randn(K, batch, 1, 28, 28).astype(np.float32))
    ys = paddle.to_tensor(
        rng.randint(0, 10, (K, batch)).astype(np.int64))
    n_disp = max(1, steps // K)
    for _ in range(max(1, warmup // 2)):
        lv = step_f(xs, ys)
    first_f = float(np.asarray(lv._value)[0])
    dts_f = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(n_disp):
            lv = step_f(xs, ys)
        last_f = float(np.asarray(lv._value)[-1])  # sync
        dts_f.append((time.perf_counter() - t0) / n_disp)
    _check_decreasing("mnist_fused", first_f, last_f)
    dt_f = float(np.median(dts_f))
    r["steps_per_dispatch"] = K
    r["fused_imgs_s"] = round(batch * K / dt_f, 1)
    r["fused_speedup"] = round((batch * K / dt_f) / (batch / dt), 3)

    # async-checkpoint robustness tax (ISSUE 6): the SAME plain step
    # loop, now snapshotting full training state (params + live opt
    # slots) through the background writer every CKPT_EVERY steps —
    # still far more aggressive than any production cadence (the EDL
    # default is time-based, 900 s). The delta vs the plain loop
    # above is the elastic-checkpointing overhead the trajectory
    # tracks (<2% target; the step-boundary device->host copy is the
    # only on-thread cost, serialization + disk ride the writer
    # thread).
    import shutil
    import tempfile

    from paddle_tpu.incubate.checkpoint import CheckpointManager

    CKPT_EVERY = 10
    ck_dir = tempfile.mkdtemp(prefix="bench_ckpt_")
    mgr = CheckpointManager(dir=ck_dir, save_steps=CKPT_EVERY,
                            max_num=2, async_write=True)
    try:
        g = 0
        for _ in range(warmup):
            loss = step(x, y)
        dts_c = []
        for _ in range(WINDOWS):
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = step(x, y)
                g += 1
                mgr.maybe_save(
                    lambda: {"model": dict(net.state_dict()),
                             "slots": step._opt_state},
                    global_step=g)
            float(loss.item())  # sync
            dts_c.append((time.perf_counter() - t0) / steps)
        dt_c = float(np.median(dts_c))
        r["ckpt_save_steps"] = CKPT_EVERY
        r["ckpt_async_imgs_s"] = round(batch / dt_c, 1)
        r["ckpt_overhead_pct"] = round((dt_c / dt - 1) * 100, 2)
    finally:
        mgr.close()
        shutil.rmtree(ck_dir, ignore_errors=True)

    # live introspection tax (ISSUE 18): the SAME plain step loop
    # with the debug server armed on an ephemeral port — the delta
    # vs the plain loop proves the serve thread is off the hot path
    # (an idle accept() should be unmeasurable; measured, not
    # assumed)
    from paddle_tpu.monitor import server as _mserver

    srv = None
    try:
        srv = _mserver.serve(port=0, host="127.0.0.1")
    except OSError:
        pass
    if srv is not None:
        try:
            for _ in range(warmup):
                loss = step(x, y)
            dts_s = []
            for _ in range(WINDOWS):
                t0 = time.perf_counter()
                for _ in range(steps):
                    loss = step(x, y)
                float(loss.item())  # sync
                dts_s.append((time.perf_counter() - t0) / steps)
            dt_s = float(np.median(dts_s))
            r["serve_port"] = srv.port
            r["serve_imgs_s"] = round(batch / dt_s, 1)
            r["serve_overhead_pct"] = round((dt_s / dt - 1) * 100, 2)
        finally:
            _mserver.stop_server()
    return r


def bench_resnet50(on_tpu):
    # r3 probe notes (v5e single chip): NHWC == NCHW e2e (XLA:TPU
    # canonicalizes conv layouts; measured 2294 vs 2291 imgs/s), so the
    # gains came from (a) one-pass BN statistics (E[x],E[x^2] fused into
    # one activation read, ops/norm_ops.py) ~+9%, (b) batch 64->128
    # ~+17%. r5: framework measures AT raw-XLA parity — pure-jax NHWC
    # resnet50 (benchmarks/parity_resnet_jax.py) records 2,682 imgs/s
    # on the same chip vs 2,621 through the full framework (−2.3%);
    # B=256 (2,572) and B=192 (2,431) are no faster, and the step
    # profile (benchmarks/artifacts/resnet50_step_summary.json) shows
    # the time in BN-stat reductions + conv fusions — the remaining
    # MFU gap is XLA:TPU's conv pipeline, not framework overhead.
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    batch = 128 if on_tpu else 2
    size = 224 if on_tpu else 32
    steps, warmup = (60, 5) if on_tpu else (2, 1)  # r3 weak #1: 20
    # timed steps was inside the jitter envelope; 60 x 5 windows
    net = resnet50()
    if on_tpu:
        net = amp.decorate(net, level="O2", dtype="bfloat16")
    ce = nn.CrossEntropyLoss()
    opt = optim.Momentum(learning_rate=0.01, momentum=0.9,
                         parameters=net.parameters(),
                         multi_precision=on_tpu)
    step = TrainStepCompiler(net, opt, lambda o, y: ce(o, y))
    rng = np.random.RandomState(0)
    import jax.numpy as jnp

    dt_in = jnp.bfloat16 if on_tpu else jnp.float32
    x = paddle.to_tensor(
        rng.randn(batch, 3, size, size).astype(np.float32))
    x._value = x._value.astype(dt_in)
    y = paddle.to_tensor(rng.randint(0, 1000, (batch,)).astype(np.int64))
    dt, first, last, dts = _measure(step, (x, y), steps, warmup)
    _check_decreasing("resnet50", first, last)
    # ResNet-50 fwd 4.09 GF/img at 224x224 (published), fwd+bwd ~3x
    fl = 3 * 4.09e9 * batch
    return _pack(round(batch / dt, 1), "imgs/s", dts, _mfu(fl, dt),
                 program=step._perf_name, flops=fl)


class _SynthImageNet:
    """ImageNet-shaped synthetic dataset for the pipeline-fed bench:
    one preallocated image per worker (index-cheap __getitem__), so
    the measured cost is collation + shm-ring transport + H2D — the
    DataLoader machinery itself — not numpy RNG throughput."""

    def __init__(self, n, size):
        rng = np.random.RandomState(0)
        self.n = n
        self.base = rng.randn(3, size, size).astype(np.float32)
        self.labels = rng.randint(0, 1000, (n,)).astype(np.int64)

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return self.base, self.labels[i]


def bench_resnet50_pipeline(on_tpu):
    """Pipeline-fed config (r4 weak #2 made this honest).

    Three measurements:
      * loader_view_imgs_s — zero-copy delivery rate of the
        multiprocess shm-ring machinery (4 workers): batches stack
        directly into ring slots and deserialize as slot views
        (protocol-5 out-of-band), trainer touches each batch. This is
        the DataLoader-machinery rate.
      * loader_imgs_s — same loader with user-OWNED batches (one
        detach memcpy per batch). The claim "the input pipeline
        sustains the synthetic device rate" is tested against THIS
        number; when the host can't reach it the note records the
        measured shortfall and the host core count (a 77 MB/batch
        pipeline needs at least one host copy; on a single-core bench
        host that copy bounds the rate regardless of worker count).
      * value (e2e imgs/s) — the same loader FEEDING the compiled
        step. In this harness the chip sits behind a network tunnel,
        so per-step H2D of a 77 MB batch is tunnel-bound (seconds) —
        an environment artifact, not a framework cost: on locally
        attached TPU, PCIe moves 77 MB in ~5 ms against a ~60 ms
        step.
    """
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.io import DataLoader
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    batch = 128 if on_tpu else 2
    size = 224 if on_tpu else 32
    net = resnet50()
    if on_tpu:
        net = amp.decorate(net, level="O2", dtype="bfloat16")
    ce = nn.CrossEntropyLoss()
    opt = optim.Momentum(learning_rate=0.01, momentum=0.9,
                         parameters=net.parameters(),
                         multi_precision=on_tpu)
    step = TrainStepCompiler(net, opt, lambda o, y: ce(o, y))
    import os

    import jax.numpy as jnp

    dt_in = jnp.bfloat16 if on_tpu else jnp.float32
    # 128x3x224x224 f32 = 77 MB/batch: needs a bigger shm-ring slot
    # than the 64 MB default
    os.environ.setdefault("FLAGS_dataloader_shm_slot_mb", "128")
    n_loader = 40 if on_tpu else 4
    warm_l = 5 if on_tpu else 1
    ds = _SynthImageNet((n_loader + warm_l) * batch, size)

    def _np_collate_pair(b):
        xs, ys = zip(*b)
        return np.stack(xs), np.stack(ys)

    # worker pool auto-sized from the host (ISSUE 8: saturate a
    # multi-core host without per-machine tuning)
    from paddle_tpu.io import _auto_num_workers

    n_workers = _auto_num_workers()

    # (1a) machinery rate: zero-copy slot views straight off the rings
    from paddle_tpu.io.worker import MultiprocessLoader

    mpl = MultiprocessLoader(ds, _np_collate_pair, n_workers, 2, 128,
                             None, 0, False, batch_size=batch,
                             default_collate=True)
    idx = [list(range(i * batch, (i + 1) * batch))
           for i in range(n_loader + warm_l)]
    gen = mpl.run_epoch(idx)
    for _ in range(warm_l):
        next(gen)
    t0 = time.perf_counter()
    got = 0
    for xb, yb in gen:
        got += 1
        _ = xb[0, 0, 0, 0]  # touch: the view is real delivered data
    view_dt = (time.perf_counter() - t0) / max(got, 1)
    mpl.shutdown()
    view_rate = round(batch / view_dt, 1)

    # (1b) user-owned host delivery rate (one detach memcpy per batch)
    loader_host = DataLoader(ds, batch_size=batch, num_workers=-1,
                             use_shared_memory=True, drop_last=True,
                             collate_fn=_np_collate_pair)
    it = iter(loader_host)
    for _ in range(warm_l):
        next(it)
    t0 = time.perf_counter()
    got = 0
    for x, y in it:
        got += 1
    loader_dt = (time.perf_counter() - t0) / max(got, 1)
    loader_rate = round(batch / loader_dt, 1)

    loader = DataLoader(ds, batch_size=batch, num_workers=-1,
                        use_shared_memory=True, drop_last=True,
                        persistent_workers=True,
                        prefetch_to_device=2)
    # (2) e2e: loader feeding the compiled step through the async
    # device-feed stage (prefetch_to_device=2): H2D for batch i+1
    # issues from a background thread while the chip runs batch i
    # (few steps — each carries a tunnel-bound 77 MB H2D in this
    # harness)
    steps, warmup, windows = (4, 1, 2) if on_tpu else (2, 1, 1)
    it = iter(loader)
    dts = []

    def _next_step():
        nonlocal it
        try:
            x, y = next(it)
        except StopIteration:
            it = iter(loader)
            x, y = next(it)
        x._value = x._value.astype(dt_in)
        return step(x, y)

    for _ in range(warmup):
        loss = _next_step()
    first = float(loss.item())
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = _next_step()
        last = float(loss.item())
        dts.append((time.perf_counter() - t0) / steps)
    _check_decreasing("resnet50_pipeline", first, last)
    dt = float(np.median(dts))
    # MFU for the pipeline-fed config too (ISSUE 8: MFU per config) —
    # same per-image FLOPs as the synthetic resnet50 config
    fl = 3 * 4.09e9 * batch
    r = _pack(round(batch / dt, 1), "imgs/s", dts, _mfu(fl, dt),
              program=step._perf_name, flops=fl)
    r["loader_view_imgs_s"] = view_rate
    r["loader_imgs_s"] = loader_rate
    r["host_cpus"] = os.cpu_count()
    r["loader_workers"] = n_workers
    r["prefetch_to_device"] = 2
    # the sustains-the-device-rate claim is checked, not asserted:
    # record truthfully whether the owned-batch rate meets the
    # synthetic device rate measured by the resnet50 config (r4 weak
    # #2: the note previously CLAIMED it while the number refuted it)
    r["note"] = (
        "loader_view_imgs_s = shm-ring machinery (zero-copy views); "
        "loader_imgs_s = user-owned batches (one detach copy) — "
        "compare THIS to the resnet50 config's imgs/s for the "
        "sustains-the-device-rate claim; on a single-core bench host "
        "the mandatory per-batch copies bound it regardless of worker "
        "count. e2e value is tunnel-H2D-bound in this harness.")
    return r


def bench_bert(on_tpu):
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.optimizer as optim
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.text.models.bert import BertConfig, BertForPretraining

    # r3 probe: batch 8->32 amortizes the fixed per-step cost
    # (68.7k -> 71.5k tok/s); hidden-768 matmuls are the ceiling
    # (K~=hidden GEMMs measure ~45-60 TF/s on this chip vs 147+ at
    # K=4096).
    paddle.seed(0)
    if on_tpu:
        cfg = BertConfig(dropout=0.0)  # bert-base
        batch, seq, steps, warmup = 32, 512, 12, 3
    else:
        cfg = BertConfig(vocab_size=512, hidden_size=128, num_layers=2,
                         num_heads=2, ffn_hidden=256, max_seq_len=128,
                         dropout=0.0)
        batch, seq, steps, warmup = 2, 128, 2, 1
    import paddle_tpu.nn as nn

    class BertPretrainStep(nn.Layer):
        """Fixed-signature wrapper so the whole batch is jit-traceable."""

        def __init__(self, cfg):
            super().__init__()
            self.m = BertForPretraining(cfg)

        def forward(self, ids, tt, labels):
            return self.m(ids, token_type_ids=tt, masked_lm_labels=labels)

    model = BertPretrainStep(cfg)
    if on_tpu:
        model = amp.decorate(model, level="O2", dtype="bfloat16")
    opt = optim.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                      weight_decay=0.01, multi_precision=on_tpu)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (batch, seq)).astype(np.int64))
    step = TrainStepCompiler(model, opt, loss_fn=None)
    tt = paddle.to_tensor(np.zeros((batch, seq), np.int64))
    dt, first, last, dts = _measure(step, (ids, tt, ids), steps, warmup)
    _check_decreasing("bert", first, last)
    fl = 6 * _param_count(model) * batch * seq
    return _pack(round(batch * seq / dt, 1), "tokens/s", dts,
                 _mfu(fl, dt), program=step._perf_name, flops=fl)


def bench_gpt2(on_tpu):
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.optimizer as optim
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    # r5 sweep (benchmarks/exp_gpt2.py): scan_unroll=24 (full unroll of
    # the layer stack) is worth +18% over the scan — the r4 profile's
    # 45% "scan body" share carried ~1.4 ms/iteration of loop overhead
    # plus dynamic-update-slice traffic saving residuals; unrolled, XLA
    # schedules across layer boundaries. Partial unroll is WORSE (u4:
    # 18.5k) and u8 OOMs. remat=False at B=4 still beats remat at
    # larger B (r3); B=6 is step-linear (no gain). CE is
    # logsumexp-gather (no [B,S,V] f32 materialization).
    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                        num_heads=16, ffn_hidden=4096, max_seq_len=1024,
                        dropout=0.0, remat=False, use_flash_attention=True,
                        scan_unroll=24)
        batch, seq, steps, warmup = 4, 1024, 20, 3  # x5 windows
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, ffn_hidden=256, max_seq_len=128,
                        dropout=0.0, remat=False, use_flash_attention=False)
        batch, seq, steps, warmup = 4, 128, 5, 1

    model = GPTForCausalLM(cfg)
    if on_tpu:
        model = amp.decorate(model, level="O2", dtype="bfloat16")
    opt = optim.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                      weight_decay=0.01, multi_precision=on_tpu)
    step = TrainStepCompiler(model, opt, loss_fn=None)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                          (batch, seq)).astype(np.int32))
    dt, first, last, dts = _measure(step, (ids, labels), steps, warmup)
    _check_decreasing("gpt2", first, last)
    fl = 6 * _param_count(model) * batch * seq
    return _pack(round(batch * seq / dt, 1), "tokens/s", dts,
                 _mfu(fl, dt), program=step._perf_name, flops=fl)


def bench_ernie(on_tpu):
    """ERNIE through the hybrid-parallel compiler (BASELINE config 5:
    Fleet mp+pp). On a single chip the mesh is 1-device (mp=pp=1) —
    the same code path the multichip dryrun runs with real axes."""
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.amp as amp
    import paddle_tpu.optimizer as optim
    from paddle_tpu.distributed import build_mesh, set_mesh
    from paddle_tpu.jit.distributed import DistributedTrainStepCompiler
    from paddle_tpu.text.models.ernie import (ErnieConfig,
                                              ErnieForPretraining)

    # r3 probe: batch sweep peaked at B=8 (77.1k); r5 re-sweep with the
    # full-sequence flash blocks moved the optimum: A/B/A/B measured
    # B=12 at 87.2k twice vs B=8 at 83-85k (+~3.5%) — the faster
    # attention shifted the per-step fixed-cost balance.
    paddle.seed(0)
    if on_tpu:
        cfg = ErnieConfig(vocab_size=18000, hidden_size=768,
                          num_layers=12, num_heads=12, ffn_hidden=3072,
                          max_seq_len=512, dropout=0.0)
        batch, seq, steps, warmup = 12, 512, 15, 3
    else:
        cfg = ErnieConfig(vocab_size=512, hidden_size=128, num_layers=2,
                          num_heads=2, ffn_hidden=256, max_seq_len=128,
                          dropout=0.0)
        batch, seq, steps, warmup = 2, 128, 2, 1
    model = ErnieForPretraining(cfg)
    if on_tpu:
        model = amp.decorate(model, level="O2", dtype="bfloat16")
    opt = optim.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                      weight_decay=0.01, multi_precision=on_tpu)
    mesh = build_mesh({"dp": 1, "pp": 1, "mp": -1})
    set_mesh(mesh)
    step = DistributedTrainStepCompiler(model, opt, loss_fn=None,
                                        mesh=mesh)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (batch, seq)).astype(np.int64))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                          (batch, seq)).astype(np.int64))
    dt, first, last, dts = _measure(step, (ids, labels), steps, warmup)
    _check_decreasing("ernie", first, last)
    set_mesh(None)
    fl = 6 * _param_count(model) * batch * seq
    return _pack(round(batch * seq / dt, 1), "tokens/s", dts,
                 _mfu(fl, dt), program=step._perf_name, flops=fl)


def _itl_ms(gaps):
    """p50/p99 inter-token latency (ms) off raw second-gaps — ONE
    implementation for the serving config and its resilience twin
    (ISSUE 15 satellite; previously two ad-hoc sorted-list copies).
    Routes through monitor.Histogram and ASSERTS the histogram
    quantiles agree with the sorted-list convention they replaced on
    the same data, within one log-bucket of resolution — so the
    Histogram the runtime exports is provably the number the bench
    used to report."""
    from paddle_tpu.core.monitor import Histogram

    h = Histogram("bench/itl_us")
    for g in gaps:
        h.observe(g * 1e6)
    sg = sorted(gaps) or [0.0]
    out = {}
    for key, q in (("itl_p50_ms", 0.5), ("itl_p99_ms", 0.99)):
        exact_ms = 1e3 * sg[min(len(sg) - 1, int(len(sg) * q))]
        hist_ms = h.quantile(q) / 1e3 if gaps else 0.0
        # one bucket's width of tolerance (plus 10us of float slack
        # for near-zero CPU-smoke gaps)
        ratio = 10.0 ** (1.0 / h.per_decade)
        assert (hist_ms <= exact_ms * ratio + 0.01
                and hist_ms >= exact_ms / ratio - 0.01), (
            f"histogram {key} {hist_ms}ms disagrees with sorted-list "
            f"{exact_ms}ms beyond one bucket ({ratio:.3f}x)")
        out[key] = round(hist_ms, 3)
    return out


def _bench_serve_spec(model, prompts, sampling, max_batch, spec_k=4):
    """ISSUE 19 twin: the SAME mixed-length request set (plus a
    shared 48-token system prefix, so the prefix cache has full
    blocks to share) through (a) a plain k=1/no-cache engine and
    (b) a speculative-decoding + prefix-caching engine. Both are
    measured at steady state — wave 2 of the same engine, after
    wave 1 paid the XLA compiles and published the shareable prefix
    blocks — the regime a long-lived serving replica actually runs
    in. Reports tokens/s + p50/p99 ITL for both, acceptance rate and
    prefill-tokens-saved; the emitted tokens are asserted identical
    to the k=1 baseline, the house discipline."""
    from paddle_tpu.core import monitor as _cmon
    from paddle_tpu.inference.serving import LLMEngine

    rng = np.random.RandomState(19)
    vocab = model.config.vocab_size
    prefix = list(rng.randint(1, vocab, 48))
    twin_prompts = [prefix + list(p) for p in prompts]

    def run(**kw):
        eng = LLMEngine(model, max_batch=max_batch, **kw)

        def wave():
            ids = [eng.add_request(p, sampling=sampling)
                   for p in twin_prompts]
            t0 = time.perf_counter()
            while eng.has_unfinished():
                eng.step()
            dt = time.perf_counter() - t0
            gaps, outs = [], []
            for i in ids:
                req = eng.get_request(i)
                ts = req.token_times
                gaps.extend(b - a for a, b in zip(ts, ts[1:]))
                outs.append(req.output_ids)
            return outs, gaps, dt

        wave()               # compiles + prefix-block registration
        outs, gaps, dt = wave()
        assert not eng.check_drained(), "spec twin leaked KV blocks"
        return outs, gaps, dt, sum(len(o) for o in outs) / dt

    base_outs, base_gaps, _, base_tps = run()
    keys = ("serve/spec/proposed", "serve/spec/accepted",
            "serve/prefix/hits", "serve/prefix/blocks_shared",
            "serve/prefix/prefill_tokens_saved")
    before = {k: _cmon.stat_get(k) for k in keys}
    spec_outs, spec_gaps, spec_dt, spec_tps = run(
        spec_k=spec_k, prefix_cache=True)
    assert spec_outs == base_outs, \
        "speculative/prefix twin diverged from the greedy baseline"
    d = {k: _cmon.stat_get(k) - before[k] for k in keys}
    assert spec_tps > base_tps, (
        f"speculative decoding did not improve steady-state "
        f"throughput: {spec_tps:.1f} vs {base_tps:.1f} tokens/s")
    out = {"value": round(spec_tps, 1), "unit": "tokens/s",
           "window_spread": [round(spec_dt, 6)],
           "spec_k": spec_k,
           "baseline_tokens_s": round(base_tps, 1),
           "speedup_vs_k1": round(spec_tps / base_tps, 3),
           "accept_rate": round(
               d["serve/spec/accepted"]
               / max(1, d["serve/spec/proposed"]), 4),
           "proposed": d["serve/spec/proposed"],
           "accepted": d["serve/spec/accepted"],
           "prefix_hits": d["serve/prefix/hits"],
           "blocks_shared": d["serve/prefix/blocks_shared"],
           "prefill_tokens_saved":
               d["serve/prefix/prefill_tokens_saved"]}
    out.update(_itl_ms(spec_gaps))
    base_itl = _itl_ms(base_gaps)
    out["baseline_itl_p50_ms"] = base_itl["itl_p50_ms"]
    out["baseline_itl_p99_ms"] = base_itl["itl_p99_ms"]
    return out


def bench_serving(on_tpu):
    """ISSUE 11: the serving engine under mixed-length generation
    traffic — continuous batching (the LLMEngine default) against a
    static-batching twin (admit a batch, drain it, admit the next),
    same requests, same pools. Reports generated tokens/s plus the
    p50/p99 INTER-TOKEN latency the scheduler's interleaving policy
    actually delivers to a streaming client. Grows two riders: the
    ISSUE-13 goodput-under-chaos twin and the ISSUE-19 speculative-
    decoding + prefix-caching twin (`_bench_serve_spec`, embedded as
    extra.serve_spec by main())."""
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import LLMEngine, SamplingParams
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024,
                        num_layers=24, num_heads=16, ffn_hidden=4096,
                        max_seq_len=1024, dropout=0.0,
                        use_flash_attention=True)
        lens, new_tokens, max_batch = (16, 64, 192, 384, 17, 96,
                                       256, 33), 64, 8
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=4, ffn_hidden=128, max_seq_len=128,
                        dropout=0.0, use_flash_attention=False)
        lens, new_tokens, max_batch = (3, 17, 9, 33, 5, 24, 12,
                                       7), 12, 4
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, cfg.vocab_size, n)) for n in lens]
    sampling = SamplingParams(max_new_tokens=new_tokens)

    def run(static):
        eng = LLMEngine(model, max_batch=max_batch,
                        static_batching=static)
        ids = [eng.add_request(p, sampling=sampling) for p in prompts]
        t0 = time.perf_counter()
        while eng.has_unfinished():
            eng.step()
        dt = time.perf_counter() - t0
        gaps = []
        for i in ids:
            ts = eng.get_request(i).token_times
            gaps.extend(b - a for a, b in zip(ts, ts[1:]))
        total = sum(len(eng.get_request(i).output_ids) for i in ids)
        assert not eng.check_drained(), "bench leaked KV blocks"
        return total / dt, gaps, dt

    cb_tps, gaps, cb_dt = run(static=False)
    sb_tps, _, _ = run(static=True)
    r = _pack(round(cb_tps, 1), "tokens/s", [cb_dt])
    r.update(_itl_ms(gaps))
    r["static_batching_tokens_s"] = round(sb_tps, 1)
    r["cb_vs_static"] = round(cb_tps / sb_tps, 3) if sb_tps else 0.0

    # disarmed-path provenance (ISSUE 19): the baseline runs above
    # never armed speculation or prefix caching, so they must leave
    # ZERO serve/spec/* + serve/prefix/* counters behind — the same
    # zero-overhead contract the sanitize/chaos gates enforce
    from paddle_tpu.core import monitor as _cmon
    leaked = {k: v for k, v in _cmon.registry.snapshot().items()
              if k.startswith(("serve/spec/", "serve/prefix/"))}
    assert not leaked, (
        "k=1/no-cache serving runs left spec/prefix counters behind "
        f"(disarmed paths must be free): {leaked}")
    r["spec"] = _bench_serve_spec(model, prompts, sampling, max_batch)

    # ISSUE-13 goodput-under-chaos twin: the SAME traffic through a
    # 2-replica Router with a serve_decode fault storm armed (OOM
    # churn + one replica kill) and tight queues — tokens/s, p50/p99
    # inter-token latency, shed rate and failover count, against the
    # clean continuous-batching number above. Embedded as
    # extra.serve_resilience by main(), so every perf record is
    # provably chaos-annotated (which faults, how many triggers, and
    # what they cost).
    from paddle_tpu.inference.serving import (EngineOverloaded,
                                              Router)
    from paddle_tpu.monitor import chaos as _chaos

    keys = ("serve/shed", "serve/failovers", "serve/drains",
            "serve/deadline_aborts", "serve/oom_evictions")
    base = {k: _cmon.stat_get(k) for k in keys}
    router = Router(model, replicas=2, max_batch=max(2, max_batch // 2),
                    max_queue=1)
    sheds = 0
    try:
        t0 = time.perf_counter()
        with _chaos.inject("serve_decode", "resource_exhausted",
                           after=4, every=5, times=3), \
                _chaos.inject("serve_decode", "raise", after=12,
                              times=1):
            ids = []
            for p in prompts:
                while True:
                    try:
                        ids.append(router.submit(p,
                                                 sampling=sampling))
                        break
                    except EngineOverloaded:
                        sheds += 1      # shed-then-retry
                        time.sleep(0.05)
            router.wait(ids, timeout_s=600)
            storm_dt = time.perf_counter() - t0
            storm_gaps, storm_total = [], 0
            for i in ids:
                req = router.get_request(i)
                ts = req.token_times
                storm_gaps.extend(b - a for a, b in zip(ts, ts[1:]))
                storm_total += len(req.output_ids)
                router.release(i)
        assert not router.check_drained(), \
            "resilience twin leaked KV blocks"
    finally:
        router.shutdown()
    deltas = {k: _cmon.stat_get(k) - base[k] for k in keys}
    storm_tps = storm_total / storm_dt if storm_dt else 0.0
    r["resilience"] = {
        "storm_tokens_s": round(storm_tps, 1),
        "goodput_vs_clean": (round(storm_tps / cb_tps, 3)
                             if cb_tps else 0.0),
        **_itl_ms(storm_gaps),
        "sheds": sheds,
        "shed_rate": round(sheds / max(1, sheds + len(ids)), 4),
        "failovers": deltas["serve/failovers"],
        "counters": deltas,
        "storm": ("serve_decode:resource_exhausted:after=4:every=5:"
                  "times=3;serve_decode:raise:after=12:times=1"),
    }
    return r


def bench_linalg(on_tpu):
    """ISSUE 12: the distributed linear-algebra tier — SUMMA matmul
    GFLOP/s on the full device grid plus Cholesky/TSQR wall times,
    each against the single-device jnp.linalg reference. The
    comm/linalg counters land in extra.linalg via main()'s snapshot,
    and the twin timings say whether distribution paid for itself at
    this size (on the CPU smoke it usually cannot — the number is a
    trajectory anchor, not a win claim)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed import build_mesh, get_mesh, set_mesh
    from paddle_tpu.linalg import dist as dla

    from paddle_tpu.core import monitor as _cmon

    n_dev = len(jax.devices())
    size = 2048 if on_tpu else 256
    prev = get_mesh()
    axes = ({"dp": 2, "mp": -1} if n_dev >= 4
            else {"dp": max(n_dev, 1)})
    set_mesh(build_mesh(axes))
    # the comm counters are process-cumulative and earlier configs
    # (ernie's hybrid compiler, serving) also move them — snapshot a
    # DELTA around this config so extra.linalg attributes only the
    # linalg algorithms' own collective traffic
    _comm_keys = ("comm/broadcast/bytes", "comm/broadcast/calls",
                  "comm/all_gather/bytes", "comm/all_gather/calls",
                  "comm/all_reduce/bytes", "comm/all_reduce/calls")
    comm0 = {k: _cmon.stat_get(k) for k in _comm_keys}
    try:
        rng = np.random.RandomState(0)
        a = rng.standard_normal((size, size)).astype(np.float32)
        m0 = rng.standard_normal((size, size)).astype(np.float32)
        spd = (m0 @ m0.T + size * np.eye(size)).astype(np.float32)
        tall = rng.standard_normal((size * 8, 32)).astype(np.float32)

        def timed(fn, iters=3):
            # block on the warmup: async dispatch would otherwise
            # bleed the warmup's device time into the timed window
            # (the CostModel.profile_measure discipline)
            jax.block_until_ready(fn())
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn()
            jax.block_until_ready(out)
            return (time.perf_counter() - t0) / iters

        A, B = dla.shard(a), dla.shard(a)
        dt_mm = timed(lambda: dla.matmul(A, B).value)
        gflops = 2 * size ** 3 / dt_mm / 1e9
        S = dla.shard(spd)
        dt_chol = timed(lambda: dla.cholesky(S).value)
        Tq = dla.shard(tall, layout="rows")
        dt_qr = timed(lambda: dla.qr(Tq)[0].value)
        # single-device references (same shapes, plain jnp on dev 0)
        dev = jax.devices()[0]
        aj = jax.device_put(a, dev)
        sj = jax.device_put(spd, dev)
        tj = jax.device_put(tall, dev)
        ref_mm = timed(lambda: jnp.matmul(aj, aj))
        ref_chol = timed(lambda: jnp.linalg.cholesky(sj))
        ref_qr = timed(lambda: jnp.linalg.qr(tj))
        r = _pack(round(gflops, 2), "summa_gflops", [dt_mm])
        r["size"] = size
        r["grid"] = repr(dla.grid())
        r["cholesky_ms"] = round(dt_chol * 1e3, 3)
        r["tsqr_ms"] = round(dt_qr * 1e3, 3)
        r["ref_matmul_ms"] = round(ref_mm * 1e3, 3)
        r["ref_cholesky_ms"] = round(ref_chol * 1e3, 3)
        r["ref_qr_ms"] = round(ref_qr * 1e3, 3)
        r["dist_vs_ref_matmul"] = (round(ref_mm / dt_mm, 4)
                                   if dt_mm else 0.0)
        r["comm"] = {k: _cmon.stat_get(k) - comm0[k]
                     for k in _comm_keys}
        return r
    finally:
        set_mesh(prev)
        dla.clear_program_cache()


def bench_qcomm(on_tpu):
    """ISSUE 14: the quantized-collective twin — the SAME dp training
    run through the explicit fp32 allreduce island and the int8
    error-feedback one (distributed.compress). Records the measured
    wire-bytes ratio (comm/all_reduce/wire_bytes deltas — the
    compression is priced, not asserted), the step-time delta (on
    the CPU smoke the quantize arithmetic usually COSTS time; the
    wire win needs real ICI), and the final-loss delta (the quality
    tax). Embedded as extra.qcomm by main()."""
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as optim
    from paddle_tpu.core import monitor as _cmon
    from paddle_tpu.distributed import build_mesh, get_mesh, set_mesh
    from paddle_tpu.jit.distributed import DistributedTrainStepCompiler

    n_dev = len(jax.devices())
    steps = 24 if on_tpu else 12
    hidden = 2048 if on_tpu else 256
    prev = get_mesh()
    keys = ("comm/all_reduce/bytes", "comm/all_reduce/wire_bytes")

    rng = np.random.RandomState(0)
    xs = [rng.randn(2 * n_dev, 64).astype(np.float32)
          for _ in range(steps)]
    ys = [rng.randn(2 * n_dev, 8).astype(np.float32)
          for _ in range(steps)]

    def run(spec):
        paddle.seed(0)
        mesh = build_mesh({"dp": n_dev})
        set_mesh(mesh)
        model = nn.Sequential(nn.Linear(64, hidden), nn.ReLU(),
                              nn.Linear(hidden, 8))
        opt = optim.AdamW(learning_rate=1e-2,
                          parameters=model.parameters())
        step = DistributedTrainStepCompiler(
            model, opt, loss_fn=lambda o, t: ((o - t) ** 2).mean(),
            mesh=mesh, comm_compress=spec)
        c0 = {k: _cmon.stat_get(k) for k in keys}
        loss = step(paddle.to_tensor(xs[0]),
                    paddle.to_tensor(ys[0]))  # compile + step 0
        losses = [float(loss.item())]
        t0 = time.perf_counter()
        for x, y in zip(xs[1:], ys[1:]):
            loss = step(paddle.to_tensor(x), paddle.to_tensor(y))
        losses.append(float(loss.item()))
        dt = (time.perf_counter() - t0) / (steps - 1)
        return {"first_loss": round(losses[0], 6),
                "final_loss": round(losses[-1], 6),
                "step_ms": round(dt * 1e3, 3),
                "comm": {k: _cmon.stat_get(k) - c0[k] for k in keys}}

    try:
        fp32 = run("fp32")
        int8 = run("int8:ef")
        ratio = (int8["comm"]["comm/all_reduce/wire_bytes"]
                 / max(fp32["comm"]["comm/all_reduce/wire_bytes"], 1))
        r = _pack(round(ratio, 4), "wire_bytes_ratio",
                  [int8["step_ms"] / 1e3])
        r["devices"] = n_dev
        r["fp32"] = fp32
        r["int8_ef"] = int8
        r["step_time_delta_ms"] = round(
            int8["step_ms"] - fp32["step_ms"], 3)
        r["final_loss_delta"] = round(
            abs(int8["final_loss"] - fp32["final_loss"]), 6)
        return r
    finally:
        set_mesh(prev)


def main(argv=None):
    import jax

    argv = list(sys.argv[1:] if argv is None else argv)
    baseline = "--baseline" in argv
    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    suite = {
        "mnist_lenet": bench_mnist,
        "resnet50": bench_resnet50,
        "resnet50_pipeline": bench_resnet50_pipeline,
        "bert_base": bench_bert,
        "gpt2_345m": bench_gpt2,
        "ernie": bench_ernie,
        "serving": bench_serving,
        "linalg": bench_linalg,
        "qcomm": bench_qcomm,
    }
    results = {}
    for name, fn in suite.items():
        try:
            r = fn(on_tpu)
            # configs without a published stand-in (serving) record 0
            r["vs_baseline"] = (round(r["value"] / BASELINES[name], 4)
                                if on_tpu and name in BASELINES
                                else 0.0)
            results[name] = r
            print(f"[bench] {name}: {r['value']} {r['unit']} "
                  f"(vs_baseline {r['vs_baseline']})", file=sys.stderr)
        except Exception as e:  # record, don't lose the other configs
            results[name] = {"error": f"{type(e).__name__}: {e}"}
            print(f"[bench] {name} FAILED: {e}", file=sys.stderr)

    # full telemetry trail of the run (jit compile counters, comm
    # bytes, io + step stats) — the StatRegistry snapshot the monitor
    # exporter would flush, embedded so every bench record carries it
    try:
        from paddle_tpu import monitor as _monitor

        results["telemetry"] = _monitor.telemetry_snapshot()
        # lint-cleanliness of the run, called out separately from the
        # full snapshot: analysis/<code>/findings counters say whether
        # the benchmarked programs tripped any PTA diagnostics (ISSUE
        # 2), so the perf trajectory records clean-vs-dirty runs
        results["analysis"] = {
            k: v for k, v in results["telemetry"]["stats"].items()
            if k.startswith("analysis/")}
        # failure-forensics health, called out like analysis/: ring
        # drops, watchdog fires and dump bundles written during the
        # bench say whether the run was clean or left evidence behind
        # (ISSUE 3)
        results["flight"] = {
            k: v for k, v in results["telemetry"]["stats"].items()
            if k.startswith("flight/")}
        # latency-hiding pipeline attribution (ISSUE 4): how many XLA
        # dispatches covered how many train steps, and what the device
        # prefetcher moved/hid — the counters that say WHERE a
        # throughput delta came from
        results["pipeline"] = {
            k: v for k, v in results["telemetry"]["stats"].items()
            if k.startswith("io/device_prefetch/")
            or k in ("io/h2d_us", "jit/dispatches", "jit/steps",
                     "jit/steps_per_dispatch")}
        # memory trajectory (ISSUE 5): device allocated/peak gauges,
        # per-program HBM footprints (mem/program/<fn>/*) and the
        # step-boundary gauges — BENCH_r06+ records track peak-HBM
        # alongside throughput so a perf win that costs memory
        # headroom is visible in the same record
        results["memory"] = {
            k: v for k, v in results["telemetry"]["stats"].items()
            if k.startswith(("mem/", "step/mem/"))}
        # elastic-checkpointing robustness tax (ISSUE 6): writer
        # throughput/drops during the bench plus the measured
        # step-time overhead (mnist ckpt_overhead_pct) — BENCH_r06+
        # tracks what fault tolerance costs alongside what perf wins
        results["ckpt"] = {
            k: v for k, v in results["telemetry"]["stats"].items()
            if k.startswith("ckpt/")}
        # chaos/resilience provenance (ISSUE 7): chaos/* proves the
        # run was fault-free (or names exactly what was injected),
        # and comm/retries + train/nonfinite_* + io/workers/* +
        # io/bad_samples + amp/scale/* record what the self-healing
        # layers absorbed — a perf number with hidden retries or
        # skipped steps is not a clean perf number
        results["resilience"] = {
            k: v for k, v in results["telemetry"]["stats"].items()
            if k.startswith(("chaos/", "io/workers/", "amp/scale/"))
            or k in ("comm/retries", "io/bad_samples",
                     "train/nonfinite_skips",
                     "train/nonfinite_stops")}
        # MFU campaign provenance (ISSUE 8): the persistent
        # compile-cache counters plus this run's TOTAL compile time —
        # a second run with a warm PADDLE_COMPILE_CACHE_DIR shows
        # persistent_cache hits > 0 and a measurably lower
        # total_compile_us (the warm-vs-cold delta the acceptance
        # tracks); pallas_fusion records whether the fused kernel
        # library was armed for these numbers, so fused and unfused
        # records can't be confused in the trajectory
        import os as _os

        stats = results["telemetry"]["stats"]
        try:
            from paddle_tpu.incubate.nn import pallas as _pallas

            fusion = _pallas.fusion_enabled()
        except Exception:
            fusion = False
        results["compile"] = {
            "total_compile_us": sum(
                v for k, v in stats.items()
                if k.endswith("/compile_us")),
            "persistent_cache": {
                k: v for k, v in stats.items()
                if k.startswith("jit/persistent_cache/")},
            "cache_dir_set": bool(
                _os.environ.get("PADDLE_COMPILE_CACHE_DIR")),
            "pallas_fusion": fusion,
        }
        # runtime sanitizer provenance (ISSUE 10): which PADDLE_SANITIZE
        # families were armed for this run plus every sanitize/*,
        # numerics/* (the PTA09x probe gauges) and PTA04x-09x
        # findings counter
        from paddle_tpu.monitor import sanitize as _sanitize

        results["sanitize"] = {
            "armed": _sanitize.families(),
            "counters": {
                k: v for k, v in stats.items()
                if k.startswith(("sanitize/", "numerics/",
                                 "analysis/PTA04",
                                 "analysis/PTA05", "analysis/PTA06",
                                 "analysis/PTA07",
                                 "analysis/PTA08",
                                 "analysis/PTA09"))}}
        # SLO alert provenance (ISSUE 20): which PADDLE_ALERTS rules
        # were armed for this run, each rule's terminal state, and
        # every alerts/* + serve/autoscale/* counter — a bench round
        # that burned its SLOs (or silently grew replicas) names it
        from paddle_tpu.monitor import alerts as _alerts

        results["alerts"] = {
            "armed": [r.name for r in _alerts.rules()],
            "rules": [r.describe() for r in _alerts.rules()],
            "counters": {
                k: v for k, v in stats.items()
                if k.startswith(("alerts/", "serve/autoscale/"))}}
        # serving-engine attribution (ISSUE 11): request/token
        # volumes, prefill vs decode wall time, KV-pool occupancy
        # and the eviction counts behind the serving config's
        # tokens/s — a throughput number that hid pool thrash or
        # admission starvation is not a clean number
        results["serve"] = {
            k: v for k, v in stats.items()
            if k.startswith("serve/")}
        # serving-resilience twin (ISSUE 13): the serving config's
        # goodput-under-chaos record — tokens/s + p50/p99 ITL with a
        # serve_decode fault storm (OOM churn + a replica kill)
        # armed, shed rate and failover count, vs the clean
        # continuous-batching number. A serving perf record that
        # never names its failure behavior under load is only half a
        # record (the 2605.25645 tail-behavior argument)
        srv = results.get("serving")
        if isinstance(srv, dict) and "resilience" in srv:
            results["serve_resilience"] = srv.pop("resilience")
        # speculative-decoding + prefix-cache twin (ISSUE 19): the
        # serving config's steady-state record with spec_k=4 drafting
        # + copy-on-write prefix sharing armed — tokens/s and p50/p99
        # ITL vs the k=1/no-cache baseline on the same request set,
        # acceptance rate and prefill-tokens-saved. A gateable config
        # of its own: regress.py picks extra.serve_spec.value up off
        # the trail automatically
        if isinstance(srv, dict) and "spec" in srv:
            results["serve_spec"] = srv.pop("spec")
        # tail-latency trajectories (ISSUE 15): the serving
        # histograms' full bucket summaries + p50/p95/p99 (ms), so
        # BENCH rounds carry latency DISTRIBUTIONS, not just
        # throughput — the serving and resilience configs above both
        # fed these (TTFT, inter-token, queue-wait, e2e)
        from paddle_tpu.core.monitor import snapshot_quantile

        results["latency"] = {
            name: {
                "count": snap["count"],
                "p50_ms": round(
                    snapshot_quantile(snap, 0.5) / 1e3, 3),
                "p95_ms": round(
                    snapshot_quantile(snap, 0.95) / 1e3, 3),
                "p99_ms": round(
                    snapshot_quantile(snap, 0.99) / 1e3, 3),
                "hist": snap,
            }
            for name, snap in (results["telemetry"].get("hists")
                               or {}).items()
            if name.startswith("serve/hist/")}
        # distributed-linalg attribution (ISSUE 12): program counts
        # and bytes processed behind the linalg config's GFLOP/s.
        # linalg/* counters only the dist tier produces; the comm
        # volume (which other configs also move) is recorded as a
        # per-config DELTA inside bench_linalg's own record
        # (results['linalg']['comm']) — the collective traffic is
        # the algorithm, so a perf record without it is
        # unexplainable. Keyed linalg_counters: results['linalg'] is
        # the config record itself
        results["linalg_counters"] = {
            k: v for k, v in stats.items()
            if k.startswith("linalg/")}
        # compute attribution (ISSUE 16): the roofline ledger behind
        # every MFU column — per-program compiler-reported FLOPs/bytes
        # (perf/program/*), measured dispatch quantiles, achieved
        # FLOP/s, per-program MFU against the SAME peak table the
        # config MFU columns use, and the roofline verdict. Plus
        # analytic-vs-compiler FLOPs drift per config: the published
        # formulas the MFU columns are built on, sanity-checked
        # against what XLA says the program actually executes — a
        # drifting ratio means the MFU trajectory is mispriced
        from paddle_tpu.monitor import perf as _perf

        perf_rep = _perf.perf_report()
        drift = {}
        for cname, rec in results.items():
            if not isinstance(rec, dict) or "program" not in rec:
                continue
            prog = rec["program"]
            an = rec.get("analytic_flops_per_step")
            comp = (perf_rep["programs"].get(prog) or {}).get("flops")
            drift[cname] = {
                "program": prog,
                "analytic_flops": an,
                "compiler_flops": comp,
                "ratio": (round(an / comp, 4)
                          if an and comp else None)}
        results["perf"] = {
            "enabled": _perf.program_capture_enabled(),
            "peaks": perf_rep["peaks"],
            "programs": perf_rep["programs"],
            "flops_drift": drift,
            "gauges": {k: v for k, v in stats.items()
                       if k.startswith(("perf/", "step/attrib/"))},
        }
    except Exception as e:
        results["telemetry"] = {"error": f"{type(e).__name__}: {e}"}
    # zero-overhead contract, asserted OUTSIDE the telemetry
    # try/except so a regression actually fails the bench: like the
    # chaos `_armed` gate, disarmed sanitizers must leave NO counters
    # behind. Scoped to the counters only ARMED runtime hooks create:
    # sanitize/spec_errors records a rejected (ignored) spec, and the
    # analysis/PTA0xx findings counters are also fed by the
    # report-only static passes under PADDLE_ANALYSIS=1 — neither is
    # runtime-sanitizer overhead
    san_extra = results.get("sanitize")
    if san_extra is not None and not san_extra["armed"]:
        leaked = {k: v for k, v in san_extra["counters"].items()
                  if k.startswith(("sanitize/", "numerics/"))
                  and k != "sanitize/spec_errors"}
        assert not leaked, (
            "disarmed sanitizers left counters behind "
            f"(zero-overhead contract broken): {leaked}")
    # same contract for the alert plane (ISSUE 20): with
    # PADDLE_ALERTS unset there is no evaluator thread and no
    # autoscaler listener, so EVERY alerts/* and serve/autoscale/*
    # counter must be exactly absent (alerts/spec_errors records a
    # rejected spec — loudness, not armed overhead)
    al_extra = results.get("alerts")
    if al_extra is not None and not al_extra["armed"]:
        leaked = {k: v for k, v in al_extra["counters"].items()
                  if k != "alerts/spec_errors"}
        assert not leaked, (
            "disarmed alert/autoscale plane left counters behind "
            f"(zero-overhead contract broken): {leaked}")
    # same contract for the perf plane: PADDLE_PERF_PROGRAM=0 must
    # leave the perf/program/* ledger empty — a disarmed opt-out that
    # still pays capture compiles (or writes gauges) is not an opt-out
    perf_extra = results.get("perf")
    if isinstance(perf_extra, dict) and not perf_extra["enabled"]:
        leaked = {k: v for k, v in perf_extra["gauges"].items()
                  if k.startswith("perf/") and v}
        assert not leaked, (
            "PADDLE_PERF_PROGRAM=0 left perf gauges behind "
            f"(zero-overhead contract broken): {leaked}")

    flag = results.get("gpt2_345m", {})
    out = {
        "metric": ("gpt2_345m_train_tokens_per_sec_per_chip" if on_tpu
                   else "gpt2_tiny_cpu_smoke_tokens_per_sec"),
        "value": flag.get("value", 0.0),
        "unit": flag.get("unit", "tokens/s"),
        "vs_baseline": flag.get("vs_baseline", 0.0),
        "extra": results,
    }
    print(json.dumps(out))
    if baseline:
        # regression gate (ISSUE 16): compare THIS run against the
        # newest BENCH_r*.json trail round with window_spread-derived
        # noise bands; nonzero rc fails the bench invocation
        import tempfile

        bench_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "benchmarks")
        if bench_dir not in sys.path:
            sys.path.insert(0, bench_dir)
        import regress

        with tempfile.NamedTemporaryFile(
                "w", suffix=".json", prefix="bench_baseline_",
                delete=False) as f:
            json.dump(out, f)
            cur_path = f.name
        try:
            return regress.main(["--current", cur_path])
        finally:
            os.unlink(cur_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
