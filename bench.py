"""Benchmark entry (driver contract): prints ONE JSON line
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Flagship metric (BASELINE.md): GPT-2 345M training throughput,
tokens/sec/chip, full train step (fwd+bwd+AdamW) compiled via
TrainStepCompiler, bf16 weights/activations on the MXU.

vs_baseline: ratio against the reference stack's nominal V100 number
for Megatron-style GPT-2 345M fp16 training (~12k tokens/s/GPU) —
BASELINE.md records no published numbers, so this constant is the
documented stand-in for "CUDAPlace/V100 step time" (north star: ≥1/1.2
≈ 0.83 of it).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

V100_GPT2_345M_TOKENS_PER_SEC = 12000.0


def main():
    import jax

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as optim
    from paddle_tpu.jit import TrainStepCompiler
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                        num_heads=16, ffn_hidden=4096, max_seq_len=1024,
                        dropout=0.0, remat=True, use_flash_attention=True)
        batch, seq, steps, warmup = 8, 1024, 20, 3
    else:  # CPU smoke (driver always runs on TPU; this keeps it runnable)
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, ffn_hidden=256, max_seq_len=128,
                        dropout=0.0, remat=False, use_flash_attention=False)
        batch, seq, steps, warmup = 4, 128, 5, 1

    model = GPTForCausalLM(cfg)
    if on_tpu:
        # bf16 weights: MXU-native (reference analog: pure-fp16 O2)
        import jax.numpy as jnp

        for _, p in model.named_parameters():
            p._value = p._value.astype(jnp.bfloat16)
    opt = optim.AdamW(learning_rate=1e-4, parameters=model.parameters(),
                      weight_decay=0.01)
    step = TrainStepCompiler(model, opt, loss_fn=None)

    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (batch, seq)).astype(np.int32))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                          (batch, seq)).astype(np.int32))

    for _ in range(warmup):
        loss = step(ids, labels)
    loss.numpy()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step(ids, labels)
    loss.numpy()  # sync
    dt = (time.perf_counter() - t0) / steps
    tokens_per_sec = batch * seq / dt

    out = {
        "metric": "gpt2_345m_train_tokens_per_sec_per_chip" if on_tpu
        else "gpt2_tiny_cpu_smoke_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        # the V100 ratio only makes sense for the real 345M TPU run;
        # the CPU smoke is a different workload entirely
        "vs_baseline": (round(tokens_per_sec
                              / V100_GPT2_345M_TOKENS_PER_SEC, 4)
                        if on_tpu else 0.0),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
